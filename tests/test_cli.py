"""CLI tests: train/test/predict subcommands run in-process on toy data.

Models the reference's CLI tests (TrainTest.java etc. run Train.execute()
on SVMLight/properties fixtures).
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.cli import main
from deeplearning4j_tpu.cli.driver import load_properties
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf import layers as L


@pytest.fixture
def toy_csv(tmp_path, rng):
    """Separable 2-class CSV: 4 features + label column (last)."""
    x = np.concatenate([rng.normal(-2, 0.5, (40, 4)),
                        rng.normal(2, 0.5, (40, 4))])
    y = np.repeat([0, 1], 40)
    order = rng.permutation(80)
    p = tmp_path / "data.csv"
    with open(p, "w") as f:
        for i in order:
            f.write(",".join(f"{v:.5f}" for v in x[i]) + f",{y[i]}\n")
    return str(p)


@pytest.fixture
def toy_svmlight(tmp_path, rng):
    x = np.concatenate([rng.normal(-2, 0.5, (30, 3)),
                        rng.normal(2, 0.5, (30, 3))])
    y = np.repeat([0, 1], 30)
    p = tmp_path / "data.svm"
    with open(p, "w") as f:
        for xi, yi in zip(x, y):
            feats = " ".join(f"{j + 1}:{v:.5f}" for j, v in enumerate(xi))
            f.write(f"{yi} {feats}\n")
    return str(p)


@pytest.fixture
def conf_json(tmp_path):
    conf = (NeuralNetConfiguration.Builder().seed(7).iterations(8)
            .learning_rate(0.5).list()
            .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=8, n_out=2, activation="softmax"))
            .build())
    p = tmp_path / "conf.json"
    p.write_text(conf.to_json())
    return str(p)


class TestTrainTestPredict:
    def test_full_cycle_csv(self, tmp_path, toy_csv, conf_json, capsys):
        model_out = str(tmp_path / "model.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", model_out, "--num-classes", "2",
                   "--epochs", "3", "--batch-size", "16"])
        assert rc == 0
        assert os.path.exists(model_out)

        rc = main(["test", "-input", toy_csv, "-model", model_out,
                   "--num-classes", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Accuracy" in out

        pred_out = str(tmp_path / "preds.txt")
        rc = main(["predict", "-input", toy_csv, "-model", model_out,
                   "--num-classes", "2", "-output", pred_out])
        assert rc == 0
        preds = [int(l) for l in open(pred_out).read().split()]
        assert len(preds) == 80
        assert set(preds) <= {0, 1}

    def test_predict_probabilities_stdout(self, tmp_path, toy_csv,
                                          conf_json, capsys):
        model_out = str(tmp_path / "model.zip")
        main(["train", "-input", toy_csv, "-model", conf_json,
              "-output", model_out, "--num-classes", "2"])
        capsys.readouterr()
        rc = main(["predict", "-input", toy_csv, "-model", model_out,
                   "--num-classes", "2", "--probabilities"])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 80
        row = [float(v) for v in lines[0].split()]
        assert len(row) == 2
        np.testing.assert_allclose(sum(row), 1.0, atol=1e-3)

    def test_svmlight_with_properties(self, tmp_path, toy_svmlight, capsys):
        conf = (NeuralNetConfiguration.Builder().seed(3).iterations(8)
                .learning_rate(0.5).list()
                .layer(0, L.DenseLayer(n_in=3, n_out=8, activation="tanh"))
                .layer(1, L.OutputLayer(n_in=8, n_out=2,
                                        activation="softmax"))
                .build())
        conf_p = tmp_path / "conf.json"
        conf_p.write_text(conf.to_json())
        props = tmp_path / "run.properties"
        props.write_text(
            "# run config\ninput.format=svmlight\nbatch.size=20\n"
            "input.num.classes=2\nepochs=3\n")
        model_out = str(tmp_path / "model.zip")
        rc = main(["train", "-input", toy_svmlight, "-model", str(conf_p),
                   "-conf", str(props), "-output", model_out])
        assert rc == 0
        rc = main(["test", "-input", toy_svmlight, "-model", model_out,
                   "-conf", str(props)])
        assert rc == 0
        assert "Accuracy" in capsys.readouterr().out

    def test_trained_model_accuracy(self, tmp_path, toy_csv, conf_json):
        """End-to-end: the CLI-trained model must actually learn."""
        from deeplearning4j_tpu.utils.serializer import ModelSerializer
        from deeplearning4j_tpu.datasets.records import (
            CSVRecordReader, RecordReaderDataSetIterator)

        model_out = str(tmp_path / "model.zip")
        main(["train", "-input", toy_csv, "-model", conf_json,
              "-output", model_out, "--num-classes", "2", "--epochs", "5"])
        net = ModelSerializer.restore(model_out)
        it = RecordReaderDataSetIterator(CSVRecordReader(toy_csv), 80,
                                         num_classes=2)
        ds = it.next()
        ev = net.evaluate(ds)
        assert ev.accuracy() > 0.9


class TestCheckpointResume:
    def test_train_checkpoints_and_resumes(self, tmp_path, toy_csv,
                                           conf_json, capsys):
        """--checkpoint-dir saves per epoch; --resume continues after the
        last completed epoch (kill-anywhere fault tolerance)."""
        ck = str(tmp_path / "ckpt")
        out1 = str(tmp_path / "m1.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", out1, "--num-classes", "2",
                   "--batch-size", "16", "--epochs", "2",
                   "--checkpoint-dir", ck])
        assert rc == 0
        from deeplearning4j_tpu.utils.checkpoint import latest_step

        assert latest_step(ck) == 2
        capsys.readouterr()
        # resume at 2 of 4: exactly two more epochs run
        out2 = str(tmp_path / "m2.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", out2, "--num-classes", "2",
                   "--batch-size", "16", "--epochs", "4",
                   "--checkpoint-dir", ck, "--resume"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "resumed from checkpoint epoch 2" in out
        assert "2 epoch(s) (2 resumed)" in out
        assert latest_step(ck) == 4
        # resume when already done: zero epochs run, model still written
        out3 = str(tmp_path / "m3.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", out3, "--num-classes", "2",
                   "--batch-size", "16", "--epochs", "4",
                   "--checkpoint-dir", ck, "--resume"])
        assert rc == 0
        import os
        assert os.path.exists(out3)

    def test_resume_from_iteration_keyed_dir_rejected(self, tmp_path,
                                                      toy_csv, conf_json):
        """A checkpoint step beyond --epochs means the dir is not
        epoch-keyed (e.g. written by CheckpointIterationListener):
        refuse rather than silently run zero epochs."""
        from deeplearning4j_tpu.utils.checkpoint import save_network
        from deeplearning4j_tpu.nn.conf.neural_net import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(
                open(conf_json).read())).init()
        ck = str(tmp_path / "iter_ck")
        save_network(ck, net, step=400)
        with pytest.raises(SystemExit, match="not epoch-keyed"):
            main(["train", "-input", toy_csv, "-model", conf_json,
                  "-output", str(tmp_path / "m.zip"),
                  "--num-classes", "2", "--epochs", "4",
                  "--checkpoint-dir", ck, "--resume"])

    def test_resume_without_dir_rejected(self, tmp_path, toy_csv,
                                         conf_json):
        with pytest.raises(SystemExit, match="checkpoint-dir"):
            main(["train", "-input", toy_csv, "-model", conf_json,
                  "-output", str(tmp_path / "m.zip"),
                  "--num-classes", "2", "--resume"])

    def test_resume_without_checkpoint_trains_fresh(self, tmp_path,
                                                    toy_csv, conf_json,
                                                    capsys):
        ck = str(tmp_path / "empty_ck")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", str(tmp_path / "m.zip"),
                   "--num-classes", "2", "--batch-size", "16",
                   "--epochs", "1", "--checkpoint-dir", ck, "--resume"])
        assert rc == 0
        assert "training from scratch" in capsys.readouterr().out


class TestProperties:
    def test_load_properties(self, tmp_path):
        p = tmp_path / "x.properties"
        p.write_text("# comment\n! also comment\na=1\nb: two\n\nmalformed\n"
                     "spaced = v \n")
        props = load_properties(str(p))
        assert props == {"a": "1", "b": "two", "spaced": "v"}

    def test_flag_overrides_property(self, tmp_path, toy_csv, conf_json,
                                     capsys):
        """--batch-size flag wins over batch.size property."""
        props = tmp_path / "p.properties"
        props.write_text("batch.size=7\ninput.num.classes=2\n")
        model_out = str(tmp_path / "m.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-conf", str(props), "-output", model_out,
                   "--batch-size", "40"])
        assert rc == 0


class TestReviewRegressions:
    def test_empty_input_clean_error(self, tmp_path, toy_csv, conf_json):
        model_out = str(tmp_path / "m.zip")
        main(["train", "-input", toy_csv, "-model", conf_json,
              "-output", model_out, "--num-classes", "2"])
        empty = tmp_path / "empty.csv"
        empty.write_text("")
        with pytest.raises(SystemExit, match="no records"):
            main(["test", "-input", str(empty), "-model", model_out,
                  "--num-classes", "2"])

    def test_epochs_zero_respected(self, tmp_path, toy_csv, conf_json,
                                   capsys):
        model_out = str(tmp_path / "m.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", model_out, "--num-classes", "2",
                   "--epochs", "0"])
        assert rc == 0
        assert "0 epoch(s)" in capsys.readouterr().out

    def test_zero_based_svmlight(self, tmp_path, rng):
        # 0-based indices: feature 0 must land in column 0
        p = tmp_path / "zb.svm"
        p.write_text("1 0:5.0 2:7.0\n0 1:3.0\n")
        from deeplearning4j_tpu.cli.driver import _build_reader
        reader = _build_reader(str(p), "svmlight", zero_based=True,
                               num_features=None)
        label, x = reader.next()
        assert label == 1.0
        np.testing.assert_allclose(x, [5.0, 0.0, 7.0])

    def test_num_features_pins_width(self, tmp_path):
        p = tmp_path / "narrow.svm"
        p.write_text("0 1:1.0\n")  # max index 1, but model wants 3
        from deeplearning4j_tpu.cli.driver import _build_reader
        reader = _build_reader(str(p), "svmlight", zero_based=False,
                               num_features=3)
        _, x = reader.next()
        assert x.shape == (3,)


class TestRuntimeDispatch:
    """-runtime local|mesh|multihost (Train.java:75,128 parity) — the mesh
    path executes on the 8-device virtual CPU mesh."""

    def test_mesh_runtime_trains_and_saves(self, tmp_path, toy_csv,
                                           conf_json, capsys):
        import jax

        assert len(jax.devices()) == 8  # conftest virtual mesh
        model_out = str(tmp_path / "model_mesh.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "--epochs", "3",
                   "-runtime", "mesh"])
        assert rc == 0
        assert "runtime=mesh" in capsys.readouterr().out
        rc = main(["test", "-input", toy_csv, "-model", model_out,
                   "--batch-size", "16", "--num-classes", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float([l for l in out.splitlines()
                     if "Accuracy" in l][0].split()[-1])
        assert acc > 0.9

    def test_mesh_runtime_device_cap(self, tmp_path, toy_csv, conf_json,
                                     capsys):
        model_out = str(tmp_path / "model_mesh4.zip")
        rc = main(["train", "-input", toy_csv, "-model", conf_json,
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "-runtime", "mesh",
                   "--mesh-devices", "4"])
        assert rc == 0

    def test_runtime_property_fallback(self, tmp_path, toy_csv, conf_json,
                                       capsys):
        props = tmp_path / "train.properties"
        props.write_text("runtime=mesh\nbatch.size=16\n"
                         "input.num.classes=2\n")
        model_out = str(tmp_path / "model_prop.zip")
        rc = main(["train", "-input", toy_csv, "-conf", str(props),
                   "-model", conf_json, "-output", model_out])
        assert rc == 0
        assert "runtime=mesh" in capsys.readouterr().out

    def test_unknown_runtime_rejected(self, toy_csv, conf_json, tmp_path):
        with pytest.raises(SystemExit):
            main(["train", "-input", toy_csv, "-model", conf_json,
                  "-output", str(tmp_path / "x.zip"),
                  "-runtime", "yarn"])

    def test_train_accepts_reference_json_model(self, tmp_path, toy_csv,
                                                capsys):
        import json

        doc = json.dumps({
            "backprop": True,
            "confs": [
                {"layer": {"dense": {"nIn": 4, "nOut": 8,
                                     "activationFunction": "tanh",
                                     "learningRate": 0.5}},
                 "seed": 7, "numIterations": 8},
                {"layer": {"output": {"nIn": 8, "nOut": 2,
                                      "activationFunction": "softmax",
                                      "lossFunction": "MCXENT",
                                      "learningRate": 0.5}},
                 "seed": 7, "numIterations": 8},
            ],
        })
        ref_conf = tmp_path / "ref_conf.json"
        ref_conf.write_text(doc)
        model_out = str(tmp_path / "model_ref.zip")
        rc = main(["train", "-input", toy_csv, "-model", str(ref_conf),
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "--epochs", "3"])
        assert rc == 0

    def test_train_accepts_reference_graph_json_model(self, tmp_path,
                                                      toy_csv, capsys):
        """A reference ComputationGraphConfiguration.toJson() document
        trains through the CLI (shape-discriminated on
        vertices+networkInputs)."""
        import json

        doc = json.dumps({
            "vertices": {
                "d": {"LayerVertex": {"layerConf": {
                    "layer": {"dense": {"nIn": 4, "nOut": 8,
                                        "activationFunction": "tanh",
                                        "learningRate": 0.5}},
                    "seed": 7, "numIterations": 4}}},
                "out": {"LayerVertex": {"layerConf": {
                    "layer": {"output": {"nIn": 8, "nOut": 2,
                                         "activationFunction": "softmax",
                                         "lossFunction": "MCXENT",
                                         "learningRate": 0.5}},
                    "seed": 7, "numIterations": 4}}},
            },
            "vertexInputs": {"d": ["in"], "out": ["d"]},
            "networkInputs": ["in"], "networkOutputs": ["out"],
        })
        ref_conf = tmp_path / "ref_graph.json"
        ref_conf.write_text(doc)
        model_out = str(tmp_path / "model_graph.zip")
        rc = main(["train", "-input", toy_csv, "-model", str(ref_conf),
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "--epochs", "2"])
        assert rc == 0
        import os
        assert os.path.exists(model_out)

    def test_graph_model_predict(self, tmp_path, toy_csv, capsys):
        """predict (argmax AND --probabilities) works on a saved
        ComputationGraph model: list-of-heads output takes head 0."""
        import json

        doc = json.dumps({
            "vertices": {
                "d": {"LayerVertex": {"layerConf": {
                    "layer": {"dense": {"nIn": 4, "nOut": 8,
                                        "activationFunction": "tanh",
                                        "learningRate": 0.5}},
                    "seed": 7, "numIterations": 4}}},
                "out": {"LayerVertex": {"layerConf": {
                    "layer": {"output": {"nIn": 8, "nOut": 2,
                                         "lossFunction": "MCXENT",
                                         "learningRate": 0.5}},
                    "seed": 7, "numIterations": 4}}},
            },
            "vertexInputs": {"d": ["in"], "out": ["d"]},
            "networkInputs": ["in"], "networkOutputs": ["out"],
        })
        ref_conf = tmp_path / "g_pred.json"
        ref_conf.write_text(doc)
        model_out = str(tmp_path / "model_g_pred.zip")
        rc = main(["train", "-input", toy_csv, "-model", str(ref_conf),
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "--epochs", "2"])
        assert rc == 0
        capsys.readouterr()
        rc = main(["predict", "-input", toy_csv, "-model", model_out,
                   "--batch-size", "16", "--num-classes", "2"])
        assert rc == 0
        lines = capsys.readouterr().out.strip().splitlines()
        assert len(lines) == 80  # one argmax per example, not one total
        assert set(lines) <= {"0", "1"}
        rc = main(["predict", "-input", toy_csv, "-model", model_out,
                   "--batch-size", "16", "--num-classes", "2",
                   "--probabilities"])
        assert rc == 0
        probs = capsys.readouterr().out.strip().splitlines()
        assert len(probs) == 80 and len(probs[0].split()) == 2

    def test_graph_model_mesh_runtime_delegates(self, tmp_path, toy_csv):
        """-runtime mesh with a ComputationGraph doc must not crash in
        ParallelWrapper (which speaks the MLN sharded-step protocol):
        non-MLN models delegate to their own fit path."""
        import json

        doc = json.dumps({
            "vertices": {
                "d": {"LayerVertex": {"layerConf": {
                    "layer": {"dense": {"nIn": 4, "nOut": 8,
                                        "activationFunction": "tanh",
                                        "learningRate": 0.5}},
                    "seed": 7, "numIterations": 2}}},
                "out": {"LayerVertex": {"layerConf": {
                    "layer": {"output": {"nIn": 8, "nOut": 2,
                                         "lossFunction": "MCXENT",
                                         "learningRate": 0.5}},
                    "seed": 7, "numIterations": 2}}},
            },
            "vertexInputs": {"d": ["in"], "out": ["d"]},
            "networkInputs": ["in"], "networkOutputs": ["out"],
        })
        ref_conf = tmp_path / "ref_graph_mesh.json"
        ref_conf.write_text(doc)
        model_out = str(tmp_path / "model_graph_mesh.zip")
        rc = main(["train", "-input", toy_csv, "-model", str(ref_conf),
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "-runtime", "mesh"])
        assert rc == 0

    def test_train_accepts_yaml_model(self, tmp_path, toy_csv):
        """A YAML model document (reference toYaml conventions) trains
        through the CLI — non-JSON input routes through the YAML
        parser."""
        doc = '\n'.join([
            '---',
            'backprop: true',
            'confs:',
            '- layer:',
            '    dense:',
            '      nIn: 4',
            '      nOut: 8',
            '      activationFunction: "tanh"',
            '      learningRate: 0.5',
            '  seed: 7',
            '  numIterations: 4',
            '- layer:',
            '    output:',
            '      nIn: 8',
            '      nOut: 2',
            '      lossFunction: "MCXENT"',
            '      learningRate: 0.5',
            '  seed: 7',
            '  numIterations: 4',
        ]) + '\n'
        yconf = tmp_path / "conf.yaml"
        yconf.write_text(doc)
        model_out = str(tmp_path / "model_yaml.zip")
        rc = main(["train", "-input", toy_csv, "-model", str(yconf),
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2"])
        assert rc == 0

    def test_mesh_runtime_ragged_final_batch(self, tmp_path, conf_json, rng,
                                             capsys):
        # 20 rows with batch 16 → final ragged batch of 4 (not divisible
        # by the 8-device mesh): must train via the unsharded fallback
        x = np.concatenate([rng.normal(-2, 0.5, (10, 4)),
                            rng.normal(2, 0.5, (10, 4))])
        y = np.repeat([0, 1], 10)
        p = tmp_path / "ragged.csv"
        with open(p, "w") as f:
            for xi, yi in zip(x, y):
                f.write(",".join(f"{v:.5f}" for v in xi) + f",{yi}\n")
        model_out = str(tmp_path / "model_ragged.zip")
        rc = main(["train", "-input", str(p), "-model", conf_json,
                   "-output", model_out, "--batch-size", "16",
                   "--num-classes", "2", "-runtime", "mesh"])
        assert rc == 0

    def test_multihost_requires_coordinator(self, toy_csv, conf_json,
                                            tmp_path):
        with pytest.raises(SystemExit, match="coordinator"):
            main(["train", "-input", toy_csv, "-model", conf_json,
                  "-output", str(tmp_path / "x.zip"),
                  "-runtime", "multihost", "--num-processes", "4"])
