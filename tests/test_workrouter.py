"""Work routers + distributed trainer (reference: workrouter/
IterativeReduceWorkRouter.java, HogWildWorkRouter.java,
perform/BaseMultiLayerNetworkWorkPerformer.java,
aggregator/INDArrayAggregator; loop per DeepLearning4jDistributed)."""

import numpy as np
import pytest

from deeplearning4j_tpu.parallel import (
    DistributedTrainer,
    FileStateTracker,
    HogwildWorkRouter,
    InMemoryStateTracker,
    IterativeReduceWorkRouter,
    NetworkWorkPerformer,
    WorkerPerformer,
    average_aggregator,
)


class _ConstPerformer(WorkerPerformer):
    """Emits a fixed vector; records redistributed params. perform() sleeps
    so both workers overlap and the barrier sees updates from each."""

    def __init__(self, value):
        self.value = np.asarray(value, np.float32)
        self.received = []

    def perform(self, payload):
        import time

        time.sleep(0.05)
        return self.value

    def update(self, params):
        self.received.append(np.asarray(params))


class TestAggregator:
    def test_mean(self):
        out = average_aggregator([np.array([1.0, 2.0]),
                                  np.array([3.0, 4.0])])
        np.testing.assert_allclose(out, [2.0, 3.0])

    def test_empty_raises(self):
        with pytest.raises(ValueError):
            average_aggregator([])


class TestIterativeReduce:
    def test_barrier_waits_for_all(self):
        tr = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tr)
        router.post("w0", np.array([2.0, 2.0]))
        assert not router.step(num_workers=2)  # one of two posted
        assert router.current_params() is None
        router.post("w1", np.array([4.0, 6.0]))
        assert router.step(num_workers=2)
        np.testing.assert_allclose(router.current_params(), [3.0, 4.0])
        assert tr.updates() == {}  # cleared for the next round

    def test_updates_channel_file_backend(self, tmp_path):
        tr = FileStateTracker(str(tmp_path / "t"))
        tr.post_update("w0", np.arange(4, dtype=np.float32))
        tr.post_update("w0", np.ones(4, np.float32))
        # every post is its own entry: a fast worker's second update in one
        # round must not overwrite its first
        keys = tr.posted_update_keys()
        assert len(keys) == 2
        assert all(tr.update_worker(k) == "w0" for k in keys)
        got = tr.drain_updates()
        assert len(got) == 2
        assert tr.updates() == {} and tr.posted_update_keys() == []


class TestHogwild:
    def test_async_mix(self):
        tr = InMemoryStateTracker()
        router = HogwildWorkRouter(tr, mix=0.5)
        router.post("w0", np.array([4.0]))
        np.testing.assert_allclose(router.current_params(), [4.0])
        router.post("w1", np.array([0.0]))  # no barrier: folds immediately
        np.testing.assert_allclose(router.current_params(), [2.0])
        assert not router.step(num_workers=2)


class TestDistributedTrainer:
    def test_drains_jobs_and_averages(self):
        tr = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tr)
        values = iter([[1.0, 1.0], [3.0, 5.0]])
        trainer = DistributedTrainer(
            tr, router, lambda: _ConstPerformer(next(values)),
            num_workers=2)
        for i in range(4):
            tr.add_job({"i": i})
        params = trainer.train(timeout_s=30)
        assert params is not None
        np.testing.assert_allclose(params, [2.0, 3.0])
        assert tr.jobs(status="pending") == []
        assert len(tr.jobs(status="done")) == 4

    def test_poison_job_fails_bounded_and_raises(self):
        """A job that always raises must not kill the worker pool: bounded
        requeue, permanent failure, surfaced error."""

        class _Poison(WorkerPerformer):
            def perform(self, payload):
                if payload == "bad":
                    raise RuntimeError("boom")
                return np.array([1.0])

        tr = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tr)
        trainer = DistributedTrainer(tr, router, _Poison, num_workers=2,
                                     max_attempts=2)
        tr.add_job("bad")
        tr.add_job("ok")
        tr.add_job("ok")
        with pytest.raises(RuntimeError, match="failed permanently"):
            trainer.train(timeout_s=30)
        failed = tr.jobs(status="failed")
        assert len(failed) == 1 and failed[0].attempts == 2
        assert len(tr.jobs(status="done")) == 2  # good jobs still ran
        assert any("boom" in e for e in trainer.errors)

    def test_partial_final_round_not_discarded(self):
        """Leftover updates from an incomplete barrier round fold into the
        returned params instead of being dropped."""
        tr = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tr)
        router._publish(np.array([0.0, 0.0]))
        tr.post_update("w0", np.array([4.0, 8.0]))  # only 1 of 2 posted
        trainer = DistributedTrainer(tr, router, lambda: _ConstPerformer([0]),
                                     num_workers=2)
        params = trainer.train(timeout_s=10)
        np.testing.assert_allclose(params, [2.0, 4.0])  # mean(update, prev)

    def test_network_performer_end_to_end(self, rng):
        """Iterative-reduce training of a real net across 2 workers beats
        the initial score (the reference's TestDistributed role)."""
        from deeplearning4j_tpu.nn.conf import (NeuralNetConfiguration,
                                                Updater)
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.neural_net import (
            MultiLayerConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.datasets.dataset import DataSet

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
            .updater(Updater.ADAM).list()
            .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=8, n_out=2))
            .build()
        )
        n = 64
        x = np.concatenate([rng.normal(-2, .5, (n // 2, 4)),
                            rng.normal(2, .5, (n // 2, 4))]).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[
            np.r_[np.zeros(n // 2, int), np.ones(n // 2, int)]]

        tr = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tr)
        conf_json = conf.to_json()
        trainer = DistributedTrainer(
            tr, router, lambda: NetworkWorkPerformer(conf_json,
                                                     fit_epochs=5),
            num_workers=2)
        for s in range(0, n, 16):
            tr.add_job({"features": x[s:s + 16].tolist(),
                        "labels": y[s:s + 16].tolist()})
        params = trainer.train(timeout_s=120)
        assert params is not None

        final = MultiLayerNetwork(
            MultiLayerConfiguration.from_json(conf_json)).init()
        final.set_flat_params(params)
        acc = final.evaluate(DataSet(x, y)).accuracy()
        assert acc > 0.9, acc
