"""Regression tests for review findings: nested-param regularization,
solver flat-param ordering with 11+ layers, async iterator error propagation,
rnn_time_step output rank."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_bilstm_with_l2_trains():
    """Nested fwd/bwd param trees must survive l1_l2_penalty + updaters."""
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(0).learning_rate(0.05).l2(0.01)
        .list()
        .layer(0, L.GravesBidirectionalLSTM(n_in=4, n_out=6))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(3, 5, 4)).astype(np.float32)
    y = np.zeros((3, 5, 2), np.float32)
    y[..., 0] = 1.0
    net.fit(x, y)
    assert np.isfinite(net.score_value)


def test_solver_flat_ordering_many_layers():
    """11+ layers: lexicographic dict order ('10' < '2') must not scramble
    the flat param vector in the solver path."""
    b = NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1) \
        .iterations(3).optimization_algo(OptimizationAlgorithm.LINE_GRADIENT_DESCENT).list()
    widths = [6, 7, 8, 9, 10, 11, 12, 11, 10, 9, 8]
    prev = 5
    for i, w in enumerate(widths):
        b.layer(i, L.DenseLayer(n_in=prev, n_out=w, activation="tanh"))
        prev = w
    b.layer(len(widths), L.OutputLayer(n_in=prev, n_out=3))
    net = MultiLayerNetwork(b.build()).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(32, 5)).astype(np.float32),
                 np.eye(3)[rng.integers(0, 3, 32)].astype(np.float32))
    initial = net.score(ds)
    net.fit(ds)
    assert np.isfinite(net.score_value)
    assert net.score(ds) <= initial * 1.05  # no scrambling blow-up


def test_async_iterator_propagates_errors():
    class Boom(ListDataSetIterator):
        def next(self, num=None):
            if self._pos >= 1:
                raise RuntimeError("corrupt batch")
            return super().next(num)

    ds = DataSet(np.zeros((40, 2), np.float32), np.zeros((40, 2), np.float32))
    it = AsyncDataSetIterator(Boom(ds, batch_size=10))
    with pytest.raises(RuntimeError, match="corrupt batch"):
        consumed = 0
        while it.has_next():
            it.next()
            consumed += 1


def test_async_iterator_full_epoch():
    ds = DataSet(np.arange(80, dtype=np.float32).reshape(40, 2),
                 np.zeros((40, 2), np.float32))
    it = AsyncDataSetIterator(ListDataSetIterator(ds, batch_size=10))
    batches = [b for b in it]
    assert len(batches) == 4
    # reset works
    batches2 = [b for b in it]
    assert len(batches2) == 4
    np.testing.assert_array_equal(batches[0].features, batches2[0].features)


def test_rnn_time_step_2d_in_2d_out():
    conf = (
        NeuralNetConfiguration.Builder().seed(0).list()
        .layer(0, L.GravesLSTM(n_in=4, n_out=6))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.ones((3, 4), np.float32)
    out = net.rnn_time_step(x)
    assert out.shape == (3, 2)
    # state carried: second call differs from a cleared-state call
    o2 = np.asarray(net.rnn_time_step(x))
    net.rnn_clear_previous_state()
    o3 = np.asarray(net.rnn_time_step(x))
    assert not np.allclose(o2, o3)
