"""Regression tests for review findings: nested-param regularization,
solver flat-param ordering with 11+ layers, async iterator error propagation,
rnn_time_step output rank."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, OptimizationAlgorithm
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def test_bilstm_with_l2_trains():
    """Nested fwd/bwd param trees must survive l1_l2_penalty + updaters."""
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(0).learning_rate(0.05).l2(0.01)
        .list()
        .layer(0, L.GravesBidirectionalLSTM(n_in=4, n_out=6))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(3, 5, 4)).astype(np.float32)
    y = np.zeros((3, 5, 2), np.float32)
    y[..., 0] = 1.0
    net.fit(x, y)
    assert np.isfinite(net.score_value)


def test_solver_flat_ordering_many_layers():
    """11+ layers: lexicographic dict order ('10' < '2') must not scramble
    the flat param vector in the solver path."""
    b = NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1) \
        .iterations(3).optimization_algo(OptimizationAlgorithm.LINE_GRADIENT_DESCENT).list()
    widths = [6, 7, 8, 9, 10, 11, 12, 11, 10, 9, 8]
    prev = 5
    for i, w in enumerate(widths):
        b.layer(i, L.DenseLayer(n_in=prev, n_out=w, activation="tanh"))
        prev = w
    b.layer(len(widths), L.OutputLayer(n_in=prev, n_out=3))
    net = MultiLayerNetwork(b.build()).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(32, 5)).astype(np.float32),
                 np.eye(3)[rng.integers(0, 3, 32)].astype(np.float32))
    initial = net.score(ds)
    net.fit(ds)
    assert np.isfinite(net.score_value)
    assert net.score(ds) <= initial * 1.05  # no scrambling blow-up


def test_async_iterator_propagates_errors():
    class Boom(ListDataSetIterator):
        def next(self, num=None):
            if self._pos >= 1:
                raise RuntimeError("corrupt batch")
            return super().next(num)

    ds = DataSet(np.zeros((40, 2), np.float32), np.zeros((40, 2), np.float32))
    it = AsyncDataSetIterator(Boom(ds, batch_size=10))
    with pytest.raises(RuntimeError, match="corrupt batch"):
        consumed = 0
        while it.has_next():
            it.next()
            consumed += 1


def test_async_iterator_full_epoch():
    ds = DataSet(np.arange(80, dtype=np.float32).reshape(40, 2),
                 np.zeros((40, 2), np.float32))
    it = AsyncDataSetIterator(ListDataSetIterator(ds, batch_size=10))
    batches = [b for b in it]
    assert len(batches) == 4
    # reset works
    batches2 = [b for b in it]
    assert len(batches2) == 4
    np.testing.assert_array_equal(batches[0].features, batches2[0].features)


def test_rnn_time_step_2d_in_2d_out():
    conf = (
        NeuralNetConfiguration.Builder().seed(0).list()
        .layer(0, L.GravesLSTM(n_in=4, n_out=6))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=2))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.ones((3, 4), np.float32)
    out = net.rnn_time_step(x)
    assert out.shape == (3, 2)
    # state carried: second call differs from a cleared-state call
    o2 = np.asarray(net.rnn_time_step(x))
    net.rnn_clear_previous_state()
    o3 = np.asarray(net.rnn_time_step(x))
    assert not np.allclose(o2, o3)


# ---------------------------------------------------------------------------
# round-4 regressions: fused TBPTT equivalence, ImageLSTM state carry,
# flash causal shape guard, jitted rnn_time_step
# ---------------------------------------------------------------------------

def _char_rnn(seed=11, vocab=10, hidden=8, tbptt=6):
    from deeplearning4j_tpu.models import char_lstm

    net = char_lstm(vocab_size=vocab, hidden=hidden, layers=1,
                    tbptt_length=tbptt, seed=seed)
    return net


def _char_data(batch=3, t=18, vocab=10, seed=4):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, vocab, (batch, t))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    return DataSet(x, y)


def test_fused_tbptt_matches_window_loop():
    """The lax.scan-fused TBPTT program must take the SAME parameter
    trajectory as the per-window host loop it replaces."""
    import jax

    ds = _char_data()
    fused = _char_rnn().init()
    fused.fit(ds)  # t=18, window=6 → 3 full windows → fused path

    loop = _char_rnn().init()
    rnn_state = loop._zero_rnn_state(3)
    for start in range(0, 18, 6):
        sub = ds.slice_time(start, start + 6)
        new_rnn = loop._sgd_step(sub, rnn_state=rnn_state)
        loop._post_iteration()
        rnn_state = jax.tree_util.tree_map(jax.lax.stop_gradient, new_rnn)

    assert fused.iteration_count == loop.iteration_count == 3
    ft, lt = fused.get_param_table(), loop.get_param_table()
    for k in ft:
        np.testing.assert_allclose(ft[k], lt[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_fused_tbptt_partial_tail_window():
    """t not divisible by the window: fused head + host-loop tail."""
    ds = _char_data(t=20)  # 3 full windows of 6 + tail of 2
    net = _char_rnn().init()
    net.fit(ds)
    # fused block counts as ONE listener event but 3 iterations; tail adds 1
    assert net.iteration_count == 4
    assert np.isfinite(net.score_value)


def test_image_lstm_in_zero_rnn_state():
    """ImageLSTM must get an h/c carry in TBPTT/rnnTimeStep zero state
    (round-2 advisor: its state was silently reset every window)."""
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(0).learning_rate(0.01)
        .list()
        .layer(0, L.ImageLSTM(n_in=12, n_out=9, hidden_size=7))
        .layer(1, L.RnnOutputLayer(n_in=9, n_out=5))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    state = net._zero_rnn_state(4)
    assert set(state["0"].keys()) == {"h", "c"}
    assert state["0"]["h"].shape == (4, 7)

    from deeplearning4j_tpu.nn.conf import Updater
    g = (
        NeuralNetConfiguration.Builder()
        .seed(0).learning_rate(0.01).updater(Updater.SGD)
        .graph_builder()
        .add_inputs("in")
        .add_layer("ilstm", L.ImageLSTM(n_in=12, n_out=9), "in")
        .set_outputs("ilstm")
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    gnet = ComputationGraph(g.build()).init()
    gstate = gnet._zero_rnn_state(2)
    assert gstate["ilstm"]["h"].shape == (2, 9)  # hidden_size defaults n_out


def test_flash_causal_requires_square():
    """causal=True with tq != tkv must raise, not silently mis-mask."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.pallas.flash_attention import flash_attention

    q = jnp.zeros((1, 4, 2, 64), jnp.float32)
    k = jnp.zeros((1, 8, 2, 64), jnp.float32)
    v = jnp.zeros((1, 8, 2, 64), jnp.float32)
    with pytest.raises(ValueError, match="tq == tkv"):
        flash_attention(q, k, v, causal=True)


def test_rnn_time_step_jitted_cached():
    """rnn_time_step goes through ONE cached jitted callable."""
    net = _char_rnn().init()
    x = np.eye(10, dtype=np.float32)[np.random.default_rng(0).integers(
        0, 10, (2, 1))]
    net.rnn_time_step(x[:, 0])
    fn = net._rnn_step_fn
    net.rnn_time_step(x[:, 0])
    assert net._rnn_step_fn is fn


def test_interleaved_fit_fitsteps_output_score():
    """Donated-buffer paths interleave safely: fit, fit_steps, output,
    score, evaluate all reuse the live param tree without touching
    deleted (donated) arrays."""
    from deeplearning4j_tpu.models import mnist_mlp

    rng = np.random.default_rng(0)
    x = rng.random((32, 784), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
    ds = DataSet(x, y)
    net = mnist_mlp(hidden=16).init()
    net.fit(ds)
    out1 = np.asarray(net.output(x))
    net.fit_steps(ds, 3)
    s1 = net.score(ds)
    net.fit(ds)
    net.fit_steps(ds, 2)
    out2 = np.asarray(net.output(x))
    s2 = net.score(ds)
    assert np.isfinite(out1).all() and np.isfinite(out2).all()
    assert np.isfinite(s1) and np.isfinite(s2)
    assert net.iteration_count == 7
    acc = net.evaluate(ds).accuracy()
    assert 0.0 <= acc <= 1.0


def test_graph_interleaved_fit_fitsteps_output():
    from deeplearning4j_tpu.models import resnet18

    rng = np.random.default_rng(0)
    x = rng.random((4, 32, 32, 3), np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
    ds = DataSet(x, y)
    net = resnet18(num_classes=10).init()
    net.fit(ds)
    net.fit_steps(ds, 2)
    out = np.asarray(net.output(x)[0])
    net.fit(ds)
    assert np.isfinite(out).all()
    assert net.iteration_count == 4


@pytest.mark.parametrize("remat", [False, True])
def test_transformer_bf16_policy_no_f32_matmuls(remat):
    """Under the bf16 policy the residual stream must stay in the compute
    dtype end to end: the f32 layernorm g/b (and MLP biases) used to
    promote it to f32, silently turning every downstream matmul into an
    f32 MXU op (measured 11.9% vs 14.0% MFU on the t=1024 bench config).
    Pin the property by tracing the loss and asserting no dot_general
    takes an f32 operand — the bug class re-enters through ANY un-cast
    f32 operand touching the stream."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import TransformerLM

    lm = TransformerLM(vocab_size=64, d_model=32, num_heads=4, num_layers=2,
                       max_len=16, dtype_policy="bf16", seed=0,
                       remat=remat).init()
    tok = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)
    jaxpr = jax.make_jaxpr(lambda p, t: lm.loss(p, t))(lm.params, tok)

    offenders = []
    seen_dots = [0]

    def scan(eqns):
        for e in eqns:
            if e.primitive.name == "dot_general":
                seen_dots[0] += 1
                if any(v.aval.dtype == jnp.float32 for v in e.invars):
                    offenders.append(e)
            for sub in e.params.values():
                # closed jaxprs (pjit/scan) carry .jaxpr; remat2 carries
                # an OPEN core.Jaxpr with .eqns directly — missing it
                # would skip every matmul inside a rematted block
                if hasattr(sub, "jaxpr"):
                    scan(sub.jaxpr.eqns)
                elif hasattr(sub, "eqns"):
                    scan(sub.eqns)

    scan(jaxpr.jaxpr.eqns)
    # guard against the scan going vacuous (e.g. a new wrapper primitive
    # hiding the block body): 2 layers x 6 matmuls + unembed must be seen
    assert seen_dots[0] >= 13, f"scan only saw {seen_dots[0]} dot_generals"
    assert not offenders, (
        f"{len(offenders)} f32-operand dot_general(s) under bf16 policy; "
        "an f32 operand leaked into the residual stream")
