"""Test config: force an 8-device virtual CPU mesh BEFORE jax initializes.

The analogue of the reference's distributed-without-a-cluster strategy (Spark
tests run `local[*]` inside the JUnit JVM — SURVEY §4): sharding/pjit tests
run against 8 virtual CPU devices so multi-chip code paths execute on one
host.
"""

import os

# Force CPU: the ambient environment may point JAX_PLATFORMS at a shared TPU
# tunnel, which is slow to compile, lacks f64 support for gradient checks,
# and is not where unit tests should run.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A sitecustomize hook may have force-selected a TPU platform via
# jax.config (which overrides the env var) — override it back.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
