"""Test config: force an 8-device virtual CPU mesh BEFORE jax initializes.

The analogue of the reference's distributed-without-a-cluster strategy (Spark
tests run `local[*]` inside the JUnit JVM — SURVEY §4): sharding/pjit tests
run against 8 virtual CPU devices so multi-chip code paths execute on one
host.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(12345)
