"""UI server + listeners (reference: deeplearning4j-ui module — UiServer,
WeightResource/FlowResource/ActivationsResource/NearestNeighborsResource,
HistogramIterationListener, ConvolutionalIterationListener,
FlowIterationListener)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    ConvolutionalIterationListener,
    FlowIterationListener,
    HistogramIterationListener,
    UiServer,
    encode_png_gray,
)


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def server():
    s = UiServer(port=0)
    yield s
    s.stop()


def _dense_net():
    conf = (
        NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
        .updater(Updater.SGD).list()
        .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=8, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _iris_like(rng, n=32):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def test_histogram_listener_roundtrip(server, rng):
    net = _dense_net()
    net.set_listeners(HistogramIterationListener(
        server=server, session_id="hist-test"))
    ds = _iris_like(rng)
    for _ in range(3):
        net.fit(ds)

    data = _get(f"{server.url}/weights/data?sid=hist-test")
    assert data["iteration"] == 3
    assert np.isfinite(data["score"])
    assert "0_W" in data["parameters"] and "1_b" in data["parameters"]
    stats = data["parameters"]["0_W"]
    assert len(stats["histogram"]["counts"]) == 30
    assert stats["l2"] > 0
    # update ("gradient") panel appears from the 2nd firing on
    assert "gradients" in data and "0_W" in data["gradients"]

    hist = _get(f"{server.url}/weights/history?sid=hist-test")
    assert [row["iteration"] for row in hist] == [1, 2, 3]
    assert all(np.isfinite(row["score"]) for row in hist)


def test_histogram_listener_over_http(server, rng):
    net = _dense_net()
    net.set_listeners(HistogramIterationListener(
        url=server.url, session_id="http-test"))
    net.fit(_iris_like(rng))
    data = _get(f"{server.url}/weights/data?sid=http-test")
    assert data["iteration"] == 1
    assert "http-test" in _get(f"{server.url}/sessions")


def test_flow_listener(server, rng):
    net = _dense_net()
    net.set_listeners(FlowIterationListener(
        server=server, session_id="flow-test", frequency=1))
    net.fit(_iris_like(rng))
    flow = _get(f"{server.url}/flow/data?sid=flow-test")
    names = [n["name"] for n in flow["nodes"]]
    assert names[0] == "input"
    assert any("DenseLayer" in n for n in names)
    assert any("OutputLayer" in n for n in names)
    assert len(flow["edges"]) == 2
    # param counts: dense 4*8+8, output 8*3+3
    by_name = {n["name"]: n["params"] for n in flow["nodes"]}
    assert by_name["0_DenseLayer"] == 4 * 8 + 8
    assert by_name["1_OutputLayer"] == 8 * 3 + 3


def test_conv_listener_posts_png(server, rng):
    conf = (
        NeuralNetConfiguration.Builder().seed(0).learning_rate(0.01).list()
        .layer(0, L.ConvolutionLayer(n_in=1, n_out=4, kernel_size=(3, 3),
                                     stride=(1, 1), activation="relu"))
        .layer(1, L.OutputLayer(n_in=4 * 26 * 26, n_out=10))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(ConvolutionalIterationListener(
        server=server, session_id="conv-test", frequency=1, max_rows=2))
    x = rng.random((4, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
    net.fit(DataSet(x, y))
    act = _get(f"{server.url}/activations/data?sid=conv-test")
    assert act["image"].startswith("data:image/png;base64,")
    assert act["layer"] == 0
    assert act["shape"][0] == 2  # max_rows examples tiled


def _get_raw(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read(), r.headers.get("Content-Type")


def test_renders_endpoint_serves_latest_activation_tile(server, rng):
    """GET /renders/img (RendersResource.java:54-57 parity): after a conv
    listener posts an activation tile, the render endpoint serves it as
    a real PNG file."""
    conf = (
        NeuralNetConfiguration.Builder().seed(0).learning_rate(0.01).list()
        .layer(0, L.ConvolutionLayer(n_in=1, n_out=4, kernel_size=(3, 3),
                                     stride=(1, 1), activation="relu"))
        .layer(1, L.OutputLayer(n_in=4 * 26 * 26, n_out=10))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(ConvolutionalIterationListener(
        server=server, session_id="render-test", frequency=1, max_rows=2))
    x = rng.random((4, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
    net.fit(DataSet(x, y))
    body, ctype = _get_raw(f"{server.url}/renders/img")
    assert ctype == "image/png"
    assert body.startswith(b"\x89PNG\r\n\x1a\n")


def test_renders_update_repoints_path(server):
    """POST /renders/update (RendersResource.java:45-49 parity) — the
    target must live in the upload dir (upload-then-repoint flow);
    arbitrary filesystem paths are refused (403), closing the
    file-read hole the reference's unrestricted imagePath had."""
    import base64

    png = encode_png_gray(np.zeros((4, 4), np.uint8))
    _post(f"{server.url}/uploads/upload",
          {"filename": "custom.png",
           "content_b64": base64.b64encode(png).decode()})
    out = _post(f"{server.url}/renders/update", {"path": "custom.png"})
    assert out["status"] == "ok"
    body, ctype = _get_raw(f"{server.url}/renders/img")
    assert ctype == "image/png" and body == png
    # escaping the upload dir → 403; traversal inside it → 403 too
    for bad in ("/etc/passwd", "../../../etc/passwd"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{server.url}/renders/update", {"path": bad})
        assert ei.value.code == 403
    # missing file inside the dir → 404, not a hang or 500
    _post(f"{server.url}/renders/update", {"path": "gone.png"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get_raw(f"{server.url}/renders/img")
    assert ei.value.code == 404
    # revert to the live activation-tile bytes
    out = _post(f"{server.url}/renders/update", {"path": None})
    assert out["path"] is None


def test_uploads_roundtrip_and_handler(server):
    """POST /uploads/upload + GET /uploads/<name>
    (FileResource.java:47-88 parity, JSON transport)."""
    import base64

    seen = []
    server.upload_handler = seen.append
    try:
        payload = {"filename": "weights.bin",
                   "content_b64": base64.b64encode(b"\x00\x01abc").decode()}
        out = _post(f"{server.url}/uploads/upload", payload)
        assert out["status"] == "ok" and out["bytes"] == 5
        assert seen and seen[0].endswith("weights.bin")
        body, _ = _get_raw(f"{server.url}/uploads/weights.bin")
        assert body == b"\x00\x01abc"
        # traversal attempts collapse to basename; absent names 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_raw(f"{server.url}/uploads/no_such_file")
        assert ei.value.code == 404
        # malformed base64 → 400 with a clear message, not a 500
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{server.url}/uploads/upload",
                  {"filename": "bad.bin", "content_b64": "!!!"})
        assert ei.value.code == 400
    finally:
        server.upload_handler = None


def test_nearest_neighbors_endpoint(server, rng):
    vecs = np.eye(4, dtype=np.float32) + 0.01 * rng.normal(size=(4, 4))
    labels = ["alpha", "beta", "gamma", "delta"]
    out = _post(f"{server.url}/nearestneighbors/upload",
                {"labels": labels, "vectors": vecs.tolist()})
    assert out["count"] == 4
    hits = _get(f"{server.url}/nearestneighbors?word=alpha&k=2")
    assert len(hits) == 2
    assert hits[0]["word"] != "alpha"
    assert hits[0]["distance"] <= hits[1]["distance"]
    assert _get(f"{server.url}/nearestneighbors?word=unknown&k=2") == []


def test_tsne_and_api_endpoints(server):
    _post(f"{server.url}/tsne/upload?sid=t",
          {"coords": [[0.0, 1.0], [1.0, 0.0]], "labels": ["a", "b"]})
    got = _get(f"{server.url}/tsne/coords?sid=t")
    assert got["labels"] == ["a", "b"]
    _post(f"{server.url}/api/update?sid=t", {"hello": "world"})
    assert _get(f"{server.url}/api/data?sid=t") == {"hello": "world"}


def test_dashboard_and_404(server):
    with urllib.request.urlopen(server.url + "/", timeout=5) as r:
        body = r.read().decode()
    assert "tpu-dl4j training UI" in body
    with pytest.raises(urllib.error.HTTPError):
        _get(server.url + "/nope")


def test_png_encoder_valid():
    img = (np.arange(64, dtype=np.uint8).reshape(8, 8) * 4).astype(np.uint8)
    png = encode_png_gray(img)
    assert png[:8] == b"\x89PNG\r\n\x1a\n"
    # decodable by PIL if available; otherwise just check IHDR dims
    import struct
    w, h = struct.unpack(">II", png[16:24])
    assert (w, h) == (8, 8)
    try:
        from PIL import Image
        import io

        arr = np.asarray(Image.open(io.BytesIO(png)))
        assert arr.shape == (8, 8)
        np.testing.assert_array_equal(arr, img)
    except ImportError:
        pass
