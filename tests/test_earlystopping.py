"""Early stopping tests (TestEarlyStopping.java analogues): termination
reasons, best-model tracking, saver round-trip."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def toy(n=128, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(3, 6)) * 3
    ys = rng.integers(0, 3, n)
    x = (centers[ys] + rng.normal(size=(n, 6))).astype(np.float32)
    return DataSet(x, np.eye(3)[ys].astype(np.float32))


def net(lr=0.05):
    conf = (NeuralNetConfiguration.Builder().seed(1).learning_rate(lr)
            .updater(Updater.ADAM).list()
            .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="relu"))
            .layer(1, L.OutputLayer(n_in=12, n_out=3)).build())
    return MultiLayerNetwork(conf).init()


def test_max_epochs_termination():
    ds = toy()
    conf = (EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
            .score_calculator(DataSetLossCalculator(
                ListDataSetIterator(toy(seed=1), 64)))
            .build())
    result = EarlyStoppingTrainer(conf, net(),
                                  ListDataSetIterator(ds, 64)).fit()
    assert result.termination_reason == EarlyStoppingResult.TerminationReason.EPOCH_TERMINATION
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert np.isfinite(result.best_model_score)


def test_score_improvement_patience():
    ds = toy()
    # lr=0 → score never improves → patience trips after 2 stale epochs
    conf = (EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(
                MaxEpochsTerminationCondition(50),
                ScoreImprovementEpochTerminationCondition(2))
            .score_calculator(DataSetLossCalculator(
                ListDataSetIterator(toy(seed=1), 64)))
            .build())
    result = EarlyStoppingTrainer(conf, net(lr=0.0),
                                  ListDataSetIterator(ds, 64)).fit()
    assert "ScoreImprovement" in result.termination_details
    assert result.total_epochs <= 5


def test_divergence_guard():
    ds = toy()
    conf = (EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
            .iteration_termination_conditions(
                MaxScoreIterationTerminationCondition(1e-12))
            .score_calculator(DataSetLossCalculator(
                ListDataSetIterator(toy(seed=1), 64)))
            .build())
    result = EarlyStoppingTrainer(conf, net(),
                                  ListDataSetIterator(ds, 64)).fit()
    assert result.termination_reason == EarlyStoppingResult.TerminationReason.ITERATION_TERMINATION
    assert "MaxScore" in result.termination_details


def test_time_guard_initializes():
    cond = MaxTimeIterationTerminationCondition(1e9)
    cond.initialize()
    assert not cond.terminate(1.0)


def test_invalid_score_condition():
    cond = InvalidScoreIterationTerminationCondition()
    assert cond.terminate(float("nan"))
    assert cond.terminate(float("inf"))
    assert not cond.terminate(1.0)


def test_local_file_saver_roundtrip(tmp_path):
    ds = toy()
    saver = LocalFileModelSaver(str(tmp_path))
    conf = (EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
            .model_saver(saver)
            .score_calculator(DataSetLossCalculator(
                ListDataSetIterator(toy(seed=1), 64)))
            .build())
    result = EarlyStoppingTrainer(conf, net(),
                                  ListDataSetIterator(ds, 64)).fit()
    best = result.get_best_model()
    out = best.output(ds.features[:4])
    assert out.shape == (4, 3)


def test_best_model_is_frozen_copy():
    """The saved best model must not track later (worse) training."""
    ds = toy()
    saver = InMemoryModelSaver()
    conf = (EarlyStoppingConfiguration.Builder()
            .epoch_termination_conditions(MaxEpochsTerminationCondition(4))
            .model_saver(saver)
            .score_calculator(DataSetLossCalculator(
                ListDataSetIterator(toy(seed=1), 64)))
            .build())
    trainer = EarlyStoppingTrainer(conf, net(), ListDataSetIterator(ds, 64))
    result = trainer.fit()
    best_params = result.best_model.get_flat_params()
    trainer.network.fit(ds)  # keep training the live net
    np.testing.assert_array_equal(best_params,
                                  result.best_model.get_flat_params())


class TestDistributedEarlyStopping:
    """Early stopping OVER the data-parallel ParallelWrapper on the
    8-device virtual mesh — the BaseSparkEarlyStoppingTrainer.java:301
    composition, previously claimed in COVERAGE.md without a test."""

    def test_early_stopping_over_parallel_wrapper(self):
        import jax
        from deeplearning4j_tpu.parallel import ParallelWrapper
        from deeplearning4j_tpu.parallel.mesh import build_mesh

        assert len(jax.devices()) == 8
        model = net()
        mesh = build_mesh()
        wrapper = ParallelWrapper(model, mesh=mesh)
        assert wrapper.data_parallelism == 8

        train = toy(n=128, seed=0)
        val = toy(n=64, seed=1)
        conf = (EarlyStoppingConfiguration.Builder()
                .epoch_termination_conditions(
                    MaxEpochsTerminationCondition(12),
                    ScoreImprovementEpochTerminationCondition(3, 1e-5))
                .score_calculator(DataSetLossCalculator(
                    ListDataSetIterator([val], 64)))
                .model_saver(InMemoryModelSaver())
                .build())
        trainer = EarlyStoppingTrainer(
            conf, wrapper, ListDataSetIterator([train], 128))
        result = trainer.fit()
        assert result.best_model is not None
        assert result.total_epochs >= 1
        assert np.isfinite(result.best_model_score)
        # training went through the wrapper's sharded step on the mesh
        assert model.iteration_count == result.total_epochs
        # best model is a true copy usable standalone
        out = result.best_model.output(np.asarray(val.features))
        assert np.asarray(out).shape == (64, 3)
