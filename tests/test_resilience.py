"""Resilience layer: deterministic fault injection, unified retry/backoff,
hung-step watchdog, and their control-plane integrations (statetracker
writes, registry polls, fetcher downloads, atomic file publication)."""

import io
import json
import os
import threading
import time

import pytest

from deeplearning4j_tpu.resilience import (
    FaultInjected,
    FaultPoint,
    RetryError,
    RetryPolicy,
    StepWatchdog,
    delay,
    fail_nth,
    fail_rate,
    fail_times,
    fault_point,
    inject,
    no_jitter,
    parse_spec,
)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.utils.fileio import atomic_write_text


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


def _recording_policy(**kw):
    sleeps = []
    kw.setdefault("base_delay_s", 0.01)
    policy = RetryPolicy(sleep=sleeps.append, **kw)
    return policy, sleeps


class TestRetryPolicy:
    def test_first_try_success_no_sleep(self):
        policy, sleeps = _recording_policy(max_attempts=5)
        assert policy.call(lambda: 42) == 42
        assert sleeps == []

    def test_transient_then_success(self):
        policy, sleeps = _recording_policy(max_attempts=5, seed=0)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert policy.call(flaky) == "ok"
        assert calls["n"] == 3
        assert len(sleeps) == 2

    def test_backoff_deterministic_under_seed(self):
        p1 = RetryPolicy(max_attempts=8, base_delay_s=0.1, max_delay_s=2.0,
                         seed=7, sleep=lambda s: None)
        p2 = RetryPolicy(max_attempts=8, base_delay_s=0.1, max_delay_s=2.0,
                         seed=7, sleep=lambda s: None)
        d1 = [p1.delay_for(k) for k in range(1, 9)]
        d2 = [p2.delay_for(k) for k in range(1, 9)]
        assert d1 == d2  # same seed → identical jitter sequence
        for k, d in enumerate(d1, start=1):
            assert 0.0 <= d <= min(2.0, 0.1 * 2 ** (k - 1))

    def test_no_jitter_gives_raw_exponential(self):
        policy = RetryPolicy(max_attempts=10, base_delay_s=0.1,
                             max_delay_s=0.8, rng=no_jitter,
                             sleep=lambda s: None)
        got = [policy.delay_for(k) for k in range(1, 6)]
        assert got == pytest.approx([0.1, 0.2, 0.4, 0.8, 0.8])  # capped

    def test_full_jitter_spreads(self):
        policy = RetryPolicy(max_attempts=2, base_delay_s=1.0,
                             max_delay_s=1.0, seed=3, sleep=lambda s: None)
        draws = {round(policy.delay_for(1), 6) for _ in range(32)}
        assert len(draws) > 16  # actually jittered, not a constant

    def test_non_retryable_propagates_immediately(self):
        policy, sleeps = _recording_policy(max_attempts=5,
                                           retryable=(OSError,))
        with pytest.raises(KeyError):
            policy.call(lambda: (_ for _ in ()).throw(KeyError("nope")))
        assert sleeps == []

    def test_retryable_predicate_form(self):
        policy, sleeps = _recording_policy(
            max_attempts=3,
            retryable=lambda e: "retry-me" in str(e))
        with pytest.raises(ValueError):
            policy.call(lambda: (_ for _ in ()).throw(ValueError("other")))
        assert sleeps == []

    def test_exhaustion_raises_retry_error(self):
        policy, sleeps = _recording_policy(max_attempts=3)

        def always():
            raise OSError("down")

        with pytest.raises(RetryError) as ei:
            policy.call(always)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.last, OSError)
        assert isinstance(ei.value.__cause__, OSError)
        assert len(sleeps) == 2  # no sleep after the final attempt

    def test_deadline_bounds_attempts(self):
        clock = {"t": 0.0}

        def monotonic():
            return clock["t"]

        def sleep(s):
            clock["t"] += s

        policy = RetryPolicy(max_attempts=None, deadline_s=1.0,
                             base_delay_s=0.4, multiplier=1.0,
                             rng=no_jitter, sleep=sleep,
                             monotonic=monotonic)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise OSError("down")

        with pytest.raises(RetryError, match="deadline"):
            policy.call(always)
        # 0.4s per retry under a 1.0s budget → 3 attempts, 2 sleeps
        assert calls["n"] == 3

    def test_on_retry_hook(self):
        seen = []
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                             on_retry=lambda a, e, d: seen.append((a, str(e))),
                             sleep=lambda s: None)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("first")
            return 1

        policy.call(flaky)
        assert seen == [(1, "first")]

    def test_unbounded_policy_rejected(self):
        with pytest.raises(ValueError, match="max_attempts or deadline_s"):
            RetryPolicy(max_attempts=None, deadline_s=None)

    def test_decorator_form(self):
        policy, _ = _recording_policy(max_attempts=2)
        calls = {"n": 0}

        @policy.retrying
        def once():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("x")
            return "done"

        assert once() == "done"


# ---------------------------------------------------------------------------
# fault points
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.mark.chaos
class TestFaultPoints:
    def test_inactive_is_noop(self):
        fault_point("nothing.installed")  # no error, no state

    def test_inject_activates_and_deactivates(self):
        with inject("site.a", fail_times(100)):
            with pytest.raises(FaultInjected):
                fault_point("site.a")
            fault_point("site.b")  # other sites unaffected
        fault_point("site.a")  # deactivated on exit

    def test_inject_restores_previous_schedule(self):
        with inject("s", fail_times(100)):
            with inject("s", delay(0)):
                fault_point("s")  # inner: delay, no raise
            with pytest.raises(FaultInjected):
                fault_point("s")  # outer restored

    def test_fail_nth_fires_exactly_nth(self):
        with inject("s", fail_nth(3)):
            fault_point("s")
            fault_point("s")
            with pytest.raises(FaultInjected):
                fault_point("s")
            fault_point("s")  # 4th passes again

    def test_fail_times_fires_first_k(self):
        with inject("s", fail_times(2)):
            for _ in range(2):
                with pytest.raises(FaultInjected):
                    fault_point("s")
            fault_point("s")

    def test_custom_exception_type(self):
        with inject("s", fail_nth(1, exc=OSError)):
            with pytest.raises(OSError):
                fault_point("s")

    def test_fail_rate_deterministic(self):
        def run():
            hits = []
            with inject("s", fail_rate(0.5, seed=42)):
                for i in range(32):
                    try:
                        fault_point("s")
                        hits.append(0)
                    except FaultInjected:
                        hits.append(1)
            return hits

        first, second = run(), run()
        assert first == second  # seeded → replayable
        assert 0 < sum(first) < 32  # actually fires sometimes

    def test_delay_sleeps(self):
        with inject("s", delay(30)):
            t0 = time.monotonic()
            fault_point("s")
            assert time.monotonic() - t0 >= 0.025

    def test_fault_point_handle(self):
        fp = FaultPoint("handle.site")
        fp()  # inactive no-op
        with inject("handle.site", fail_nth(1)):
            with pytest.raises(FaultInjected):
                fp()
        assert "handle.site" in repr(fp)

    def test_parse_spec(self):
        scheds = parse_spec(
            "statetracker.write=fail_nth:2;heartbeat.post=delay:1;"
            "fetcher.download=fail_rate:0.5:9")
        assert set(scheds) == {"statetracker.write", "heartbeat.post",
                               "fetcher.download"}
        scheds["statetracker.write"]("x")  # 1st passes
        with pytest.raises(FaultInjected):
            scheds["statetracker.write"]("x")  # 2nd fires

    def test_parse_spec_rejects_garbage(self):
        with pytest.raises(ValueError, match="bad DL4J_FAULTS"):
            parse_spec("whatisthis")
        with pytest.raises(ValueError, match="bad DL4J_FAULTS"):
            parse_spec("site=unknown_schedule:1")

    def test_install_from_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_FAULTS", "env.site=fail_nth:1")
        assert faults.install_from_env() == 1
        with pytest.raises(FaultInjected):
            fault_point("env.site")
        monkeypatch.delenv("DL4J_FAULTS")
        assert faults.install_from_env() == 0


# ---------------------------------------------------------------------------
# StepWatchdog
# ---------------------------------------------------------------------------


class TestStepWatchdog:
    def test_fires_on_stall(self):
        fired = threading.Event()
        stalls = []

        def on_stall(s):
            stalls.append(s)
            fired.set()

        with StepWatchdog(deadline_s=0.05, on_stall=on_stall,
                          poll_s=0.01):
            assert fired.wait(2.0)
        assert stalls and stalls[0] >= 0.05
        assert len(stalls) == 1  # once per episode, no repeat-fire spam

    def test_beats_prevent_firing(self):
        stalls = []
        with StepWatchdog(deadline_s=0.08, on_stall=stalls.append,
                          poll_s=0.01) as wd:
            for _ in range(10):
                time.sleep(0.02)
                wd.beat()
        assert stalls == []
        assert wd.beats >= 10

    def test_new_beat_rearms(self):
        fired = threading.Event()
        stalls = []

        def on_stall(s):
            stalls.append(s)
            fired.set()

        with StepWatchdog(deadline_s=0.05, on_stall=on_stall,
                          poll_s=0.01) as wd:
            assert fired.wait(2.0)  # first stall episode
            fired.clear()
            wd.beat()  # progress resumes → re-armed
            assert fired.wait(2.0)  # second stall episode fires again
        assert len(stalls) == 2

    def test_repeat_every(self):
        stalls = []
        with StepWatchdog(deadline_s=0.03, on_stall=stalls.append,
                          poll_s=0.01, repeat_every_s=0.03):
            time.sleep(0.3)
        assert len(stalls) >= 2  # escalating re-fires during one stall

    def test_stop_idempotent_and_restartable(self):
        wd = StepWatchdog(deadline_s=10.0, poll_s=0.01)
        wd.start()
        wd.stop()
        wd.stop()  # idempotent
        wd.start()  # restart after stop
        wd.stop()

    def test_bad_deadline_rejected(self):
        with pytest.raises(ValueError):
            StepWatchdog(deadline_s=0.0)

    def test_callback_exception_does_not_kill_thread(self):
        calls = []

        def bad(s):
            calls.append(s)
            raise RuntimeError("callback bug")

        with StepWatchdog(deadline_s=0.02, on_stall=bad, poll_s=0.01,
                          repeat_every_s=0.02) as wd:
            time.sleep(0.15)
            assert wd._thread.is_alive()
        assert len(calls) >= 2  # survived its own callback raising


# ---------------------------------------------------------------------------
# fileio satellite: bare filenames + durability
# ---------------------------------------------------------------------------


class TestAtomicWriteText:
    def test_bare_filename(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        atomic_write_text("bare.json", '{"a": 1}')  # dirname("") crashed
        with open("bare.json") as f:
            assert json.load(f) == {"a": 1}

    def test_no_temp_litter_on_failure(self, tmp_path):
        target = str(tmp_path / "out.txt")

        class Boom:
            def __str__(self):
                raise RuntimeError("boom")

        with pytest.raises(TypeError):
            atomic_write_text(target, Boom())  # f.write rejects non-str
        assert os.listdir(tmp_path) == []  # tempfile cleaned up

    def test_overwrite_atomic(self, tmp_path):
        target = str(tmp_path / "cfg.json")
        atomic_write_text(target, "one")
        atomic_write_text(target, "two")
        with open(target) as f:
            assert f.read() == "two"


# ---------------------------------------------------------------------------
# control-plane integration
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestTrackerResilience:
    def test_write_faults_retried(self, tmp_path):
        from deeplearning4j_tpu.parallel import FileStateTracker

        tr = FileStateTracker(
            str(tmp_path / "t"),
            retry_policy=RetryPolicy(max_attempts=4, base_delay_s=0.001,
                                     retryable=(OSError,),
                                     sleep=lambda s: None))
        with inject("statetracker.write", fail_times(2, exc=OSError)):
            jid = tr.add_job({"x": 1})  # survives 2 injected write faults
        assert tr.jobs(status="pending")[0].job_id == jid

    def test_write_faults_exhaust(self, tmp_path):
        from deeplearning4j_tpu.parallel import FileStateTracker

        tr = FileStateTracker(
            str(tmp_path / "t"),
            retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.001,
                                     retryable=(OSError,),
                                     sleep=lambda s: None))
        with inject("statetracker.write", fail_times(10, exc=OSError)):
            with pytest.raises(RetryError):
                tr.add_job({"x": 1})

    def test_torn_job_read_retried(self, tmp_path):
        from deeplearning4j_tpu.parallel import FileStateTracker

        tr = FileStateTracker(str(tmp_path / "t"))
        jid = tr.add_job({"x": 1})
        path = tr._job_path(jid)
        with open(path) as f:
            good = f.read()

        # torn read: the reader first sees half a JSON document (the
        # non-atomic-visibility window of gcsfuse/NFS); the backoff sleep
        # doubles as "the write completes" before the retry
        def heal(_seconds):
            with open(path, "w") as f:
                f.write(good)

        tr.retry_policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                                      retryable=(ValueError,), sleep=heal)
        with open(path, "w") as f:
            f.write(good[: len(good) // 2])
        j = tr._read_job(jid)  # retries through the decode error
        assert j is not None and j.job_id == jid

    def test_heartbeat_fault_skips_beat_not_thread(self):
        from deeplearning4j_tpu.parallel import InMemoryStateTracker
        from deeplearning4j_tpu.parallel.cluster import HeartbeatMonitor

        tracker = InMemoryStateTracker()
        # every 2nd post fails — the monitor thread must survive and keep
        # posting on the other intervals
        with inject("heartbeat.post", fail_rate(0.5, seed=1)):
            with HeartbeatMonitor(tracker, "w1", interval_s=0.01):
                time.sleep(0.2)
        assert tracker.last_heartbeat("w1") is not None

    def test_registry_wait_for_rides_through_faults(self, tmp_path):
        from deeplearning4j_tpu.parallel import ConfigRegistry

        reg = ConfigRegistry(str(tmp_path / "reg"))
        reg.register("h", "t", {"lr": 0.1})
        with inject("registry.retrieve", fail_times(2, exc=OSError)):
            got = reg.wait_for(
                "h", "t",
                policy=RetryPolicy(max_attempts=5, base_delay_s=0.001,
                                   retryable=(KeyError, OSError),
                                   sleep=lambda s: None))
        assert got == {"lr": 0.1}

    def test_registry_wait_for_times_out(self, tmp_path):
        from deeplearning4j_tpu.parallel import ConfigRegistry

        reg = ConfigRegistry(str(tmp_path / "reg"))
        with pytest.raises(TimeoutError):
            reg.wait_for("h", "missing", timeout_s=0.1, poll_s=0.02)


@pytest.mark.chaos
class TestFetcherDownloadResilience:
    def _opener(self, payload=b"idx-bytes", log=None):
        def opener(url):
            if log is not None:
                log.append(url)
            return io.BytesIO(payload)

        return opener

    def test_download_retries_then_succeeds(self, tmp_path):
        from deeplearning4j_tpu.datasets.fetchers import download_file

        sleeps = []
        urls = []
        dest = str(tmp_path / "data" / "file.gz")
        with inject("fetcher.download", fail_times(2, exc=OSError)):
            out = download_file(
                "https://example.invalid/file.gz", dest,
                policy=RetryPolicy(max_attempts=4, base_delay_s=0.001,
                                   retryable=(OSError,),
                                   sleep=sleeps.append),
                opener=self._opener(log=urls))
        assert out == dest
        with open(dest, "rb") as f:
            assert f.read() == b"idx-bytes"
        assert len(sleeps) == 2  # two injected failures, two backoffs
        assert len(urls) == 1  # faults fired before the opener ran

    def test_download_exhaustion_raises_and_leaves_no_partial(self,
                                                              tmp_path):
        from deeplearning4j_tpu.datasets.fetchers import download_file

        dest = str(tmp_path / "file.gz")
        with inject("fetcher.download", fail_times(10, exc=OSError)):
            with pytest.raises(RetryError):
                download_file(
                    "https://example.invalid/file.gz", dest,
                    policy=RetryPolicy(max_attempts=3, base_delay_s=0.001,
                                       retryable=(OSError,),
                                       sleep=lambda s: None),
                    opener=self._opener())
        assert not os.path.exists(dest)
        assert os.listdir(tmp_path) == []  # no tempfile litter either

    def test_zero_egress_default(self, monkeypatch):
        from deeplearning4j_tpu.datasets import fetchers

        monkeypatch.delenv("DL4J_TPU_ALLOW_DOWNLOAD", raising=False)
        assert fetchers.downloads_allowed() is False
        assert fetchers._maybe_download_mnist("/nope",
                                              "train-images-idx3-ubyte") \
            is None


class TestAtomicWriteBytes:
    def test_round_trip_and_cleanup(self, tmp_path):
        from deeplearning4j_tpu.utils.fileio import atomic_write_bytes

        target = str(tmp_path / "blob.bin")
        atomic_write_bytes(target, lambda f: f.write(b"\x00\x01payload"))
        with open(target, "rb") as f:
            assert f.read() == b"\x00\x01payload"

        def boom(f):
            f.write(b"partial")
            raise RuntimeError("writer died")

        with pytest.raises(RuntimeError):
            atomic_write_bytes(str(tmp_path / "never.bin"), boom)
        assert sorted(os.listdir(tmp_path)) == ["blob.bin"]  # no litter


@pytest.mark.chaos
class TestReviewRegressions:
    def test_wait_for_retries_injected_faults_by_default(self, tmp_path):
        """The documented registry.retrieve injection site must be retried
        by wait_for's DEFAULT policy, not crash it (its stated contract)."""
        from deeplearning4j_tpu.parallel import ConfigRegistry

        reg = ConfigRegistry(str(tmp_path / "reg"))
        reg.register("h", "t", {"ok": 1})
        with inject("registry.retrieve", fail_times(2)):  # FaultInjected
            assert reg.wait_for("h", "t", timeout_s=5.0,
                                poll_s=0.01) == {"ok": 1}

    def test_cached_images_do_not_suppress_label_download(
            self, tmp_path, monkeypatch):
        """With images already local but labels missing, enabling
        downloads must fetch the LABEL file, not silently go synthetic."""
        from deeplearning4j_tpu.datasets import fetchers

        monkeypatch.setenv("DL4J_TPU_ALLOW_DOWNLOAD", "1")
        base = str(tmp_path / "mnist")
        os.makedirs(base)
        with open(os.path.join(base, "train-images-idx3-ubyte.gz"),
                  "wb") as f:
            f.write(b"cached")
        asked = []
        monkeypatch.setattr(
            fetchers, "download_file",
            lambda url, dest, **kw: asked.append(os.path.basename(dest))
            or dest)
        # the fetcher's per-file resolution: each file independently
        img = fetchers._first_existing(base, "train-images-idx3-ubyte") \
            or fetchers._maybe_download_mnist(base,
                                              "train-images-idx3-ubyte")
        lbl = fetchers._first_existing(base, "train-labels-idx1-ubyte") \
            or fetchers._maybe_download_mnist(base,
                                              "train-labels-idx1-ubyte")
        assert img is not None
        assert "train-labels-idx1-ubyte.gz" in asked

    def test_heartbeat_writes_skip_fsync(self, tmp_path, monkeypatch):
        """Beats are ephemeral: the durable fsync path must not run for
        them (hot-path regression guard)."""
        import deeplearning4j_tpu.utils.fileio as fileio
        from deeplearning4j_tpu.parallel import FileStateTracker

        synced = []
        real_fsync = os.fsync
        monkeypatch.setattr(
            fileio.os, "fsync",
            lambda fd: synced.append(fd) or real_fsync(fd))
        tr = FileStateTracker(str(tmp_path / "t"))
        tr.heartbeat("w1")
        assert synced == []  # no fsync on the beat path
        tr.put_meta("k", {"v": 1})
        assert synced  # durable data still fsyncs

    def test_wait_for_invalid_name_fails_fast(self, tmp_path):
        """A name-validation error is permanent: it must raise NOW, not
        spin for the whole timeout and surface as TimeoutError."""
        from deeplearning4j_tpu.parallel import ConfigRegistry

        reg = ConfigRegistry(str(tmp_path / "reg"))
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="invalid registry name"):
            reg.wait_for("../escape", "task", timeout_s=30.0)
        assert time.monotonic() - t0 < 1.0

    def test_trainer_rejects_eviction_below_beat_interval(self):
        from deeplearning4j_tpu.parallel import (
            DistributedTrainer,
            InMemoryStateTracker,
            IterativeReduceWorkRouter,
        )

        tr = InMemoryStateTracker()
        with pytest.raises(ValueError, match="heartbeat_interval_s"):
            DistributedTrainer(tr, IterativeReduceWorkRouter(tr),
                               lambda: None, eviction_timeout_s=0.5,
                               heartbeat_interval_s=1.0)

    def test_schema_mismatched_job_file_crashes_loudly(self, tmp_path):
        """Valid JSON that isn't a Job must raise (a real bug), not make
        the job silently vanish from jobs()/claim_job()."""
        from deeplearning4j_tpu.parallel import FileStateTracker

        tr = FileStateTracker(str(tmp_path / "t"))
        jid = tr.add_job({"x": 1})
        with open(tr._job_path(jid), "w") as f:
            f.write('{"not_a_job_field": true}')
        with pytest.raises(TypeError):
            tr.jobs()

    def test_bare_exception_class_retryable(self):
        """retryable=OSError (no tuple) must mean isinstance, not a
        predicate call — and must never swallow KeyboardInterrupt."""
        sleeps = []
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                             retryable=OSError, sleep=sleeps.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient")
            return "ok"

        assert policy.call(flaky) == "ok"
        with pytest.raises(ValueError):  # not an OSError: propagates
            policy.call(lambda: (_ for _ in ()).throw(ValueError("bug")))
        with pytest.raises(KeyboardInterrupt):
            policy.call(
                lambda: (_ for _ in ()).throw(KeyboardInterrupt()))

    def test_invalid_download_never_poisons_cache(self, tmp_path,
                                                  monkeypatch):
        """A mirror error page served with HTTP 200 must be discarded,
        not committed under the dataset's real name."""
        from deeplearning4j_tpu.datasets import fetchers

        monkeypatch.setenv("DL4J_TPU_ALLOW_DOWNLOAD", "1")
        base = str(tmp_path / "mnist")

        def fake_download(url, dest, **kw):
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            with open(dest, "wb") as f:
                f.write(b"<html>404 not found</html>")
            return dest

        monkeypatch.setattr(fetchers, "download_file", fake_download)
        got = fetchers._maybe_download_mnist(base,
                                             "train-images-idx3-ubyte")
        assert got is None
        assert not os.path.exists(
            os.path.join(base, "train-images-idx3-ubyte.gz"))

    def test_valid_idx_gz_accepts_real_header(self, tmp_path):
        import gzip
        import struct as _struct

        from deeplearning4j_tpu.datasets.fetchers import _valid_idx_gz

        path = str(tmp_path / "t.gz")
        with gzip.open(path, "wb") as f:
            f.write(_struct.pack(">IIII", 2051, 1, 2, 2))
            f.write(bytes(4))
        assert _valid_idx_gz(path) is True

    def test_heartbeats_do_not_consume_write_fault_schedules(
            self, tmp_path):
        """Background beats must not bump count-based schedules installed
        at statetracker.write — that site stays deterministic for DATA
        writes; beats have their own heartbeat.post site."""
        from deeplearning4j_tpu.parallel import FileStateTracker

        tr = FileStateTracker(
            str(tmp_path / "t"),
            retry_policy=RetryPolicy(max_attempts=1, base_delay_s=0.001,
                                     sleep=lambda s: None))
        with inject("statetracker.write", fail_nth(1)):
            for _ in range(5):
                tr.heartbeat("w1")  # beats pass through untouched
            with pytest.raises(RetryError):  # data write absorbs fault #1
                tr.put_meta("k", 1)
