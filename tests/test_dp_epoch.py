"""Sharded epoch pipeline: SPMD whole-epoch fusion across the data mesh.

The contract under test (perf/epoch_cache.py mesh placement +
ParallelWrapper.fit_epochs + fit_epochs(mesh=...) on both network classes),
on the conftest-forced 8-virtual-CPU-device mesh:

- the sharded fused run matches the single-device fused run's ``[E, N]``
  loss history and final params to <=1e-6 (f32) on IDENTICAL RNG key
  streams — FF, RNN (with masks), and graph networks, fsdp on and off
  (the two runs consume the same ``epoch_schedule`` stream by
  construction; only the gradient all-reduce's summation order differs);
- the cached sharded path makes exactly ONE train-program dispatch per
  epoch chunk regardless of device count;
- cache stacks are placed with the batch axis sharded over ``data``
  (B/n rows per chip) and the HBM budget check is per-shard;
- ``accum_steps=K`` produces the same update as the unaccumulated global
  batch to <=1e-6 and lets a dataset over the per-shard budget take the
  fused path;
- ``DL4J_CACHE_DTYPE=bfloat16`` narrows features/labels stacks only;
- EarlyStoppingTrainer(fuse_epochs=True) and the streaming fallback both
  route through the sharded program.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.parallel import ParallelWrapper, build_mesh
from deeplearning4j_tpu.perf.epoch_cache import (
    DeviceDataSetCache,
    DeviceMultiDataSetCache,
    effective_accum_steps,
)

TOL = dict(rtol=0, atol=1e-6)


def _ff_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
        .updater(Updater.SGD).list()
        .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build()).init()


def _ff_data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _rnn_data(n=48, t=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    lm = (np.arange(t)[None, :]
          < rng.integers(3, t + 1, n)[:, None]).astype(np.float32)
    return DataSet(x, y, None, lm)


class TestShardedCachePlacement:
    def test_batch_axis_sharded_over_data(self):
        mesh = build_mesh()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(96), 32), mesh=mesh)
        assert cache.n_shard == 8
        # every chip holds B/n = 4 rows of every batch
        shapes = {s.data.shape for s in cache.features.addressable_shards}
        assert shapes == {(3, 4, 6)}
        shapes = {s.data.shape for s in cache.labels_mask.addressable_shards}
        assert shapes == {(3, 4)}

    def test_per_shard_budget_scales_with_chip_count(self):
        """A dataset over the single-device budget fits once sharded 8
        ways (cacheable size scales linearly with chip count)."""
        data = _ff_data(512, seed=3)
        # ~40% of one full f32 copy of features+labels
        budget_mb = 0.4 * 512 * 4 * (6 + 3) / (1024 ** 2)
        assert DeviceDataSetCache.build(
            ListDataSetIterator(data, 64), budget_mb=budget_mb) is None
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(data, 64), budget_mb=budget_mb,
            mesh=build_mesh())
        assert cache is not None and cache.n_shard == 8

    def test_indivisible_batch_replicates_on_mesh(self):
        """Bucket batch 4 cannot tile 8 devices: the stacks replicate
        over the mesh (never a failed build)."""
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(8), 4), mesh=build_mesh())
        assert cache is not None
        assert cache.n_shard == 1
        shapes = {s.data.shape for s in cache.features.addressable_shards}
        assert shapes == {(2, 4, 6)}  # full copy per device

    def test_multi_cache_shards_every_head(self):
        cache = DeviceMultiDataSetCache.build(
            ListDataSetIterator(_ff_data(96), 32), mesh=build_mesh())
        assert cache.n_shard == 8
        shapes = {s.data.shape for s in cache.features[0].addressable_shards}
        assert shapes == {(3, 4, 6)}


class TestCacheDtype:
    def test_bf16_narrows_features_labels_only(self, monkeypatch):
        monkeypatch.setenv("DL4J_CACHE_DTYPE", "bfloat16")
        import jax.numpy as jnp

        cache = DeviceDataSetCache.build(ListDataSetIterator(_ff_data(), 32))
        assert cache.features.dtype == jnp.bfloat16
        assert cache.labels.dtype == jnp.bfloat16
        assert cache.labels_mask.dtype == jnp.float32  # masks stay exact

    def test_bf16_halves_the_budgeted_footprint(self, monkeypatch):
        f32 = DeviceDataSetCache.build(ListDataSetIterator(_ff_data(), 32))
        monkeypatch.setenv("DL4J_CACHE_DTYPE", "bf16")
        bf16 = DeviceDataSetCache.build(ListDataSetIterator(_ff_data(), 32))
        # features+labels halve; the (f32) masks are the remainder
        mask_bytes = bf16.labels_mask.nbytes
        assert (bf16.nbytes - mask_bytes) * 2 == f32.nbytes - mask_bytes

    def test_bf16_fits_twice_the_data(self, monkeypatch):
        data = _ff_data(512, seed=3)
        # between the bf16 footprint (f+l halved, masks+working set f32)
        # and the f32 one
        budget_mb = 0.8 * 512 * 4 * (6 + 3) / (1024 ** 2)
        assert DeviceDataSetCache.build(
            ListDataSetIterator(data, 64), budget_mb=budget_mb) is None
        monkeypatch.setenv("DL4J_CACHE_DTYPE", "bfloat16")
        assert DeviceDataSetCache.build(
            ListDataSetIterator(data, 64), budget_mb=budget_mb) is not None


class TestShardedFusedEquivalence:
    """Sharded fused run vs single-device fused run on IDENTICAL RNG key
    streams: [E, N] history and final params to <=1e-6 (the only
    difference is the all-reduce's summation order)."""

    @pytest.mark.parametrize("fsdp", [False, True])
    def test_ff(self, fsdp):
        single, sharded = _ff_net(), _ff_net()
        hist_1 = single.fit_epochs(ListDataSetIterator(_ff_data(), 32), 3)
        wrapper = ParallelWrapper(sharded, mesh=build_mesh(), fsdp=fsdp)
        hist_n = wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 3)
        np.testing.assert_allclose(np.asarray(hist_n), np.asarray(hist_1),
                                   **TOL)
        np.testing.assert_allclose(sharded.get_flat_params(),
                                   single.get_flat_params(), **TOL)
        assert sharded.iteration_count == single.iteration_count == 9

    @pytest.mark.parametrize("fsdp", [False, True])
    def test_rnn_with_masks(self, fsdp):
        data = _rnn_data()
        single, sharded = _rnn_net(), _rnn_net()
        hist_1 = single.fit_epochs(ListDataSetIterator(data, 16), 2)
        wrapper = ParallelWrapper(sharded, mesh=build_mesh(), fsdp=fsdp)
        hist_n = wrapper.fit_epochs(ListDataSetIterator(data, 16), 2)
        np.testing.assert_allclose(np.asarray(hist_n), np.asarray(hist_1),
                                   **TOL)
        np.testing.assert_allclose(sharded.get_flat_params(),
                                   single.get_flat_params(), **TOL)

    @pytest.mark.parametrize("fsdp", [False, True])
    def test_graph(self, fsdp):
        single, sharded = _ff_graph(), _ff_graph()
        hist_1 = single.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        wrapper = ParallelWrapper(sharded, mesh=build_mesh(), fsdp=fsdp)
        hist_n = wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        np.testing.assert_allclose(np.asarray(hist_n), np.asarray(hist_1),
                                   **TOL)
        for k, v in single.get_param_table().items():
            np.testing.assert_allclose(
                np.asarray(sharded.get_param_table()[k]), np.asarray(v),
                **TOL)

    def test_mesh_param_without_wrapper(self):
        """fit_epochs(mesh=...) on a bare network is the same program."""
        single, sharded = _ff_net(), _ff_net()
        hist_1 = single.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        hist_n = sharded.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2,
                                    mesh=build_mesh())
        np.testing.assert_allclose(np.asarray(hist_n), np.asarray(hist_1),
                                   **TOL)
        np.testing.assert_allclose(sharded.get_flat_params(),
                                   single.get_flat_params(), **TOL)

    def test_fsdp_state_stays_sharded_across_chunks(self):
        # hidden width 16 tiles the 8-way mesh, so FSDP shards [6, 16]
        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
            .updater(Updater.ADAM).list()
            .layer(0, L.DenseLayer(n_in=6, n_out=16, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=16, n_out=3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        wrapper = ParallelWrapper(net, mesh=build_mesh(), fsdp=True)
        wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2,
                           chunk_epochs=1)
        # out_shardings pinned: state is STILL 1/N-per-device after the
        # donated chunk programs, not silently re-replicated
        w0 = net.params["0"]["W"]
        assert any(s == "data" for s in w0.sharding.spec)


class TestOneDispatchPerChunk:
    def test_sharded_dispatch_count_matches_single_device(self):
        """Exactly ONE train-program dispatch per epoch chunk at any
        device count (here 8) — the whole point of composing sharding
        with whole-epoch fusion."""
        net = _ff_net()
        wrapper = ParallelWrapper(net, mesh=build_mesh())
        hist = wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 5)
        assert net._train_dispatches == 1  # one program for all 5 epochs
        assert hist.shape == (5, 3)
        net2 = _ff_net()
        wrapper2 = ParallelWrapper(net2, mesh=build_mesh())
        wrapper2.fit_epochs(ListDataSetIterator(_ff_data(), 32), 4,
                            chunk_epochs=1)
        assert net2._train_dispatches == 4  # 1 per chunk, not per batch

    def test_program_cached_per_shuffle_and_accum(self):
        net = _ff_net()
        wrapper = ParallelWrapper(net, mesh=build_mesh())
        wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        assert set(wrapper._epoch_steps) == {(True, 1, True, 0)}
        wrapper.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2,
                           accum_steps=4)
        assert set(wrapper._epoch_steps) == {(True, 1, True, 0), (True, 4, True, 0)}


class TestGradientAccumulation:
    def test_same_update_as_unaccumulated(self):
        base, accum = _ff_net(), _ff_net()
        hist_b = base.fit_epochs(ListDataSetIterator(_ff_data(), 32), 3)
        hist_a = accum.fit_epochs(ListDataSetIterator(_ff_data(), 32), 3,
                                  accum_steps=4)
        np.testing.assert_allclose(np.asarray(hist_a), np.asarray(hist_b),
                                   **TOL)
        np.testing.assert_allclose(accum.get_flat_params(),
                                   base.get_flat_params(), **TOL)

    def test_same_update_with_masks_and_ragged_tail(self):
        """Pad rows (ragged tail bucket-padded to 32) plus label masks:
        the microbatch reweighting must reproduce the full batch's
        masked-mean denominators exactly."""
        data = _rnn_data(40, t=5, seed=2)  # 16/16/8 -> pad rows in tail
        base, accum = _rnn_net(), _rnn_net()
        hist_b = base.fit_epochs(ListDataSetIterator(data, 16), 2)
        hist_a = accum.fit_epochs(ListDataSetIterator(data, 16), 2,
                                  accum_steps=8)
        np.testing.assert_allclose(np.asarray(hist_a), np.asarray(hist_b),
                                   **TOL)
        np.testing.assert_allclose(accum.get_flat_params(),
                                   base.get_flat_params(), **TOL)

    def test_graph_same_update(self):
        base, accum = _ff_graph(), _ff_graph()
        hist_b = base.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        hist_a = accum.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2,
                                  accum_steps=4)
        np.testing.assert_allclose(np.asarray(hist_a), np.asarray(hist_b),
                                   **TOL)
        for k, v in base.get_param_table().items():
            np.testing.assert_allclose(
                np.asarray(accum.get_param_table()[k]), np.asarray(v),
                **TOL)

    def test_accum_lets_overbudget_step_take_fused_path(self):
        """The budget's working-set term divides by K: a dataset whose
        resident+step footprint overflows at K=1 fits at K=8 and takes
        the fused path (asserted via the returned history + dispatch
        counter) instead of streaming."""
        data = _ff_data(128, seed=5)
        stack = 128 * 4 * (6 + 3)          # resident f+l bytes, 4 batches
        step = 32 * 4 * (6 + 3)            # one-batch working set
        budget_mb = (stack + step + 8 * 32) / (1024 ** 2)  # + masks, < 2*step
        a = _ff_net()
        hist = a.fit_epochs(ListDataSetIterator(data, 32), 2,
                            cache_mb=budget_mb)
        assert hist is None  # streamed: over budget unaccumulated
        b = _ff_net()
        hist = b.fit_epochs(ListDataSetIterator(data, 32), 2,
                            cache_mb=budget_mb, accum_steps=8)
        assert hist is not None and hist.shape == (2, 4)
        assert b._train_dispatches == 1

    def test_effective_accum_clamps_to_divisor(self):
        assert effective_accum_steps(8, 32) == 8
        # largest divisor of the batch <= requested, never silently 1
        assert effective_accum_steps(3, 32) == 2
        assert effective_accum_steps(6, 32) == 4
        assert effective_accum_steps(1, 32) == 1
        assert effective_accum_steps(7, 12) == 6
        assert effective_accum_steps(64, 32) == 32

    def test_env_accum_prices_the_prebuilt_cache_budget(self, monkeypatch):
        """build_epoch_cache (the EarlyStoppingTrainer path) must resolve
        DL4J_ACCUM_STEPS so the budget's working-set term is priced at
        the K the run will actually use."""
        data = _ff_data(128, seed=5)
        stack = 128 * 4 * (6 + 3)
        step = 32 * 4 * (6 + 3)
        budget_mb = (stack + step + 8 * 32) / (1024 ** 2)
        monkeypatch.setenv("DL4J_DEVICE_CACHE_MB", str(budget_mb))
        net = _ff_net()
        assert net.build_epoch_cache(ListDataSetIterator(data, 32)) is None
        monkeypatch.setenv("DL4J_ACCUM_STEPS", "8")
        assert net.build_epoch_cache(
            ListDataSetIterator(data, 32)) is not None

    def test_env_default_applies(self, monkeypatch):
        monkeypatch.setenv("DL4J_ACCUM_STEPS", "4")
        base, accum = _ff_net(), _ff_net()
        hist_b = base.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2,
                                 accum_steps=1)
        hist_a = accum.fit_epochs(ListDataSetIterator(_ff_data(), 32), 2)
        assert (True, 4, True, 0) in accum._epoch_steps
        np.testing.assert_allclose(np.asarray(hist_a), np.asarray(hist_b),
                                   **TOL)


class TestRouting:
    def test_early_stopping_fused_routes_through_sharded_program(self):
        from deeplearning4j_tpu.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            EarlyStoppingTrainer, MaxEpochsTerminationCondition)

        data = _ff_data(96, seed=7)
        net = _ff_net()
        wrapper = ParallelWrapper(net, mesh=build_mesh())
        config = (EarlyStoppingConfiguration.Builder()
                  .epoch_termination_conditions(
                      MaxEpochsTerminationCondition(3))
                  .score_calculator(
                      DataSetLossCalculator(ListDataSetIterator(data, 32)))
                  .build())
        trainer = EarlyStoppingTrainer(
            config, wrapper, ListDataSetIterator(data, 32),
            fuse_epochs=True)
        result = trainer.fit()
        assert result.total_epochs == 3
        assert net._train_dispatches == 3  # one SPMD program per epoch
        # the trainer's cache was mesh-sharded (built via the wrapper)
        assert (True, 1, True, 0) in wrapper._epoch_steps

    def test_streaming_fallback_routes_through_sharded_step(self):
        """Over budget even sharded -> per-batch streaming through the
        wrapper's sharded step, identical results to plain fit."""
        data = _ff_data(128, seed=8)
        a, b = _ff_net(), _ff_net()
        wrapper = ParallelWrapper(a, mesh=build_mesh())
        it = ListDataSetIterator(data, 32)
        hist = wrapper.fit_epochs(it, 2)
        assert hist is not None  # sanity: this dataset fits
        # now force the budget under the dataset (per-shard!) so it streams
        a2, b2 = _ff_net(), _ff_net()
        w2 = ParallelWrapper(a2, mesh=build_mesh())
        cache = a2.build_epoch_cache(ListDataSetIterator(data, 32))
        assert cache is not None
        import deeplearning4j_tpu.perf.epoch_cache as ec
        old = ec.cache_budget_mb
        ec.cache_budget_mb = lambda: 1e-6
        try:
            hist2 = w2.fit_epochs(ListDataSetIterator(data, 32), 2)
        finally:
            ec.cache_budget_mb = old
        assert hist2 is None  # streamed
        for _ in range(2):
            b2.fit(ListDataSetIterator(data, 32))
        np.testing.assert_allclose(a2.get_flat_params(),
                                   b2.get_flat_params(), rtol=2e-4,
                                   atol=1e-5)

    def test_unsupported_config_delegates_not_crashes(self):
        from deeplearning4j_tpu.nn.conf.enums import BackpropType

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.02)
            .updater(Updater.SGD).list()
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_forward_length(4).t_bptt_backward_length(4)
            .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
            .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                       loss_function=LossFunction.MCXENT))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        wrapper = ParallelWrapper(net, mesh=build_mesh())
        data = DataSet(
            np.random.default_rng(0).normal(size=(16, 8, 3)).astype(
                np.float32),
            np.eye(4, dtype=np.float32)[
                np.random.default_rng(0).integers(0, 4, (16, 8))])
        hist = wrapper.fit_epochs(ListDataSetIterator(data, 8), 2)
        assert hist is None
        assert np.isfinite(net.score_value)
