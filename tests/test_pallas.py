"""Pallas flash attention (pallas/flash_attention.py).

On CPU the kernel runs through the Pallas interpreter — same kernel code
the Mosaic compiler lowers on TPU. Equality is checked against
``ops.attention.dot_product_attention`` for forward and gradients, plus
the ring-attention integration (``impl="flash"``) on the 8-device mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.ops.attention import dot_product_attention
from deeplearning4j_tpu.pallas.flash_attention import (
    flash_attention,
    flash_attention_fwd,
)
from deeplearning4j_tpu.parallel import MeshSpec, build_mesh
from deeplearning4j_tpu.parallel.ring_attention import ring_attention


def _qkv(b, t, h, d, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    return mk(), mk(), mk()


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(2, 128, 4, 64)
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64,
                              block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_ragged_length_padding(self):
        # t not a multiple of the block size exercises kv padding masks
        q, k, v = _qkv(1, 200, 2, 32, seed=1)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_lse_is_logsumexp(self):
        q, k, v = _qkv(1, 64, 2, 32, seed=2)
        _, lse = flash_attention_fwd(q, k, v, block_q=64, block_k=64)
        scale = 1.0 / np.sqrt(32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        expected = jax.scipy.special.logsumexp(logits, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)

    def test_cross_attention_lengths(self):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, 64, 2, 32)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(2, 96, 2, 32)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 96, 2, 32)), jnp.float32)
        ref = dot_product_attention(q, k, v)
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(2, 128, 4, 32, seed=4)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=64, block_k=64) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_trains_under_jit(self):
        # one SGD step through the custom_vjp inside jit
        q, k, v = _qkv(1, 64, 2, 16, seed=5)

        @jax.jit
        def step(q):
            g = jax.grad(lambda q: jnp.mean(
                flash_attention(q, k, v, causal=True, block_q=64,
                                block_k=64) ** 2))(q)
            return q - 0.1 * g

        q2 = step(q)
        assert bool(jnp.all(jnp.isfinite(q2)))


class TestRingFlash:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        q, k, v = _qkv(2, 64, 4, 16, seed=6)
        mesh = build_mesh(MeshSpec(data=1, sequence=8))
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh, causal=causal, impl="flash")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        q, k, v = _qkv(1, 64, 2, 16, seed=7)
        mesh = build_mesh(MeshSpec(data=1, sequence=8))

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh, causal=causal,
                               impl="flash") ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=5e-4, atol=5e-4)

    def test_transformer_flash_forward_matches_xla(self):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        tokens = jnp.asarray(
            np.random.default_rng(8).integers(0, 32, (2, 64)), jnp.int32)
        lm_x = TransformerLM(vocab_size=32, d_model=32, num_heads=2,
                             num_layers=2, max_len=64, seed=0,
                             attn_impl="xla").init()
        lm_f = TransformerLM(vocab_size=32, d_model=32, num_heads=2,
                             num_layers=2, max_len=64, seed=0,
                             attn_impl="flash").init()
        lx = lm_x.forward(lm_x.params, tokens)
        lf = lm_f.forward(lm_f.params, tokens)
        np.testing.assert_allclose(np.asarray(lf), np.asarray(lx),
                                   rtol=2e-4, atol=2e-4)


class TestFlashBackwardPallas:
    """flash_backward_pallas (VMEM-resident dk/dv + dq kernels) against
    the XLA-scan flash_backward on identical inputs."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("t", [128, 200])
    def test_matches_scan_backward(self, causal, t):
        from deeplearning4j_tpu.pallas.flash_attention import (
            flash_attention_fwd, flash_backward, flash_backward_pallas)

        q, k, v = _qkv(2, t, 2, 32, seed=6)
        do = jnp.asarray(
            np.random.default_rng(7).normal(size=q.shape), jnp.float32)
        out, lse = flash_attention_fwd(q, k, v, causal=causal,
                                       block_q=64, block_k=64)
        ref = flash_backward(q, k, v, out, lse, do, causal=causal)
        got = flash_backward_pallas(q, k, v, out, lse, do, causal=causal,
                                    block_q=64, block_k=64)
        for a, b in zip(ref, got):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_cross_attention_lengths(self):
        from deeplearning4j_tpu.pallas.flash_attention import (
            flash_attention_fwd, flash_backward, flash_backward_pallas)

        rng = np.random.default_rng(8)
        q = jnp.asarray(rng.normal(size=(1, 64, 2, 32)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 160, 2, 32)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 160, 2, 32)), jnp.float32)
        do = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
        out, lse = flash_attention_fwd(q, k, v, block_q=64, block_k=64)
        ref = flash_backward(q, k, v, out, lse, do)
        got = flash_backward_pallas(q, k, v, out, lse, do,
                                    block_q=64, block_k=64)
        for a, b in zip(ref, got):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_bf16_kernel_vs_precise_scan_oracle(self):
        """Advisor r4: with BOTH sides casting matmul operands to bf16, a
        shared precision bug class cancels out. precise=True keeps the
        scan oracle's operands in f32, so the kernels are checked against
        a genuinely higher-precision independent implementation."""
        from deeplearning4j_tpu.pallas.flash_attention import (
            flash_attention_fwd, flash_backward, flash_backward_pallas)

        q, k, v = _qkv(1, 128, 2, 32, seed=21)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        do = jnp.asarray(
            np.random.default_rng(22).normal(size=q.shape), jnp.float32)
        out, lse = flash_attention_fwd(qb, kb, vb, causal=True,
                                       block_q=64, block_k=64)
        oracle = flash_backward(qb, kb, vb, out, lse, do, causal=True,
                                precise=True)
        # oracle operands really ran in f32
        assert oracle[0].dtype == jnp.float32
        got = flash_backward_pallas(qb, kb, vb, out, lse, do, causal=True,
                                    block_q=64, block_k=64)
        for a, b in zip(oracle, got):
            np.testing.assert_allclose(
                np.asarray(b, np.float32), np.asarray(a, np.float32),
                rtol=0.05, atol=0.05)

    def test_bf16_operands(self):
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(1, 128, 2, 32, seed=9)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

        def loss_ref(q, k, v):
            return jnp.sum(
                dot_product_attention(q, k, v, causal=True)
                .astype(jnp.float32) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, block_q=64,
                                block_k=64).astype(jnp.float32) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(qb, kb, vb)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(qb, kb, vb)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(
                np.asarray(b, np.float32), np.asarray(a, np.float32),
                rtol=0.1, atol=0.15)


class TestFlashBackwardOffsets:
    def test_split_query_span_grads_sum_to_full(self):
        """flash_backward's q_offset path: causal attention over t=128
        computed as two q-half calls (offsets 0 and 64) must reproduce
        the full backward — dq halves concatenate, dk/dv contributions
        sum. Pins the offset masking now that the ring path no longer
        exercises it."""
        from deeplearning4j_tpu.pallas.flash_attention import (
            flash_attention_fwd, flash_backward)

        t, half = 128, 64
        q, k, v = _qkv(1, t, 2, 32, seed=12)
        do = jnp.asarray(
            np.random.default_rng(13).normal(size=q.shape), jnp.float32)
        out, lse = flash_attention_fwd(q, k, v, causal=True,
                                       block_q=64, block_k=64)
        dq_full, dk_full, dv_full = flash_backward(
            q, k, v, out, lse, do, causal=True)

        pieces = []
        for off in (0, half):
            sl = slice(off, off + half)
            pieces.append(flash_backward(
                q[:, sl], k, v, out[:, sl], lse[:, :, sl], do[:, sl],
                causal=True, q_offset=off, k_offset=0))
        (dq0, dk0, dv0), (dq1, dk1, dv1) = pieces
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate([dq0, dq1], axis=1)),
            np.asarray(dq_full), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dk0 + dk1),
                                   np.asarray(dk_full),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(dv0 + dv1),
                                   np.asarray(dv_full),
                                   rtol=1e-4, atol=1e-4)


class TestSlidingWindow:
    @pytest.mark.parametrize("t,window", [(128, 32), (200, 50), (128, 128)])
    def test_flash_window_matches_reference(self, t, window):
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(2, t, 2, 32, seed=20)
        ref = dot_product_attention(q, k, v, causal=True, window=window)
        out = flash_attention(q, k, v, causal=True, window=window,
                              block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_flash_window_grads_match_reference(self):
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(1, 128, 2, 32, seed=21)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, causal=True, window=48) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, window=48, block_q=64,
                block_k=64) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_window_requires_causal(self):
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(1, 64, 2, 32)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, window=16)
        with pytest.raises(ValueError, match="causal"):
            dot_product_attention(q, k, v, window=16)

    def test_grouped_window_matches_repeat(self):
        from deeplearning4j_tpu.ops.attention import grouped_query_attention

        rng = np.random.default_rng(22)
        q = jnp.asarray(rng.normal(size=(1, 32, 4, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 32, 2, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, 32, 2, 16)), jnp.float32)
        ref = dot_product_attention(q, jnp.repeat(k, 2, 2),
                                    jnp.repeat(v, 2, 2),
                                    causal=True, window=8)
        got = grouped_query_attention(q, k, v, causal=True, window=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-6, atol=2e-6)

    def test_window_grads_with_fully_dead_tiles(self):
        """t=192, window=32, block 64: query block 2 never intersects key
        block 0, so the BACKWARD kernels' band skip runs in its dead
        state — a wrong skip condition would zero live dk/dv tiles."""
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(1, 192, 2, 32, seed=23)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, causal=True, window=32) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, window=32, block_q=64,
                block_k=64) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)

    def test_backward_entry_points_validate_window(self):
        from deeplearning4j_tpu.pallas.flash_attention import (
            flash_attention_fwd, flash_backward, flash_backward_pallas)

        q, k, v = _qkv(1, 64, 2, 32)
        out, lse = flash_attention_fwd(q, k, v, causal=True,
                                       block_q=64, block_k=64)
        for fn in (flash_backward, flash_backward_pallas):
            with pytest.raises(ValueError, match="causal"):
                fn(q, k, v, out, lse, q, causal=False, window=16)

    def test_strongly_banded_long_sequence(self):
        """t=512, window=64, block 64: the banded grid scans 3 of 8 key
        blocks per query block; forward AND gradients must still match
        the dense reference exactly."""
        from deeplearning4j_tpu.pallas.flash_attention import flash_attention

        q, k, v = _qkv(1, 512, 2, 32, seed=24)
        ref = dot_product_attention(q, k, v, causal=True, window=64)
        out = flash_attention(q, k, v, causal=True, window=64,
                              block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

        def loss_ref(q, k, v):
            return jnp.sum(dot_product_attention(
                q, k, v, causal=True, window=64) ** 2)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, window=64, block_q=64,
                block_k=64) ** 2)

        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-4, atol=1e-4)
