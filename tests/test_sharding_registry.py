"""One mesh for everything: the DP×TP×PP sharding registry.

The contract under test (parallel/sharding_registry.py + the fused
epoch program routed through it + TP serving), on the conftest-forced
8-virtual-CPU-device mesh:

- spec lookup is TOTAL over FF/RNN/graph/TransformerLM param leaves —
  every leaf gets an explicit PartitionSpec, and an unmapped leaf
  raises ``UnmappedLeafError`` instead of silently replicating;
- a DP×TP mesh (2×4) runs ``fit_epochs`` as ONE donated GSPMD program
  per chunk (1 dispatch, counter-asserted) with final params <= 1e-6 of
  the single-device run for FF/RNN/graph across every step variant
  (plain / accum / guard / telemetry / mixed_bf16);
- elastic reshard generalizes to TOPOLOGY changes: 8×1 → 4×2 mid-run
  lands <= 1e-6 of the uninterrupted run (arXiv 2112.01075's
  redistribute, realized as snapshot-to-host + registry re-place);
- serving shards decode + the KV slot pool over ``model`` via the SAME
  registry specs: greedy streams token-identical to the unsharded
  server, per-shard pool budget green under ``validate_cache_budget``;
- ``check_network_contracts`` resolves its declared-axes set from the
  registry the placement stamped on the network, and flags a seeded
  sparse (cond-gated) collective over an undeclared axis.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.parallel import MeshSpec, build_mesh
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from deeplearning4j_tpu.parallel.sharding_registry import (
    ShardingRegistry,
    UnmappedLeafError,
    batch_spec,
    mesh_from_env,
    parse_mesh_shape,
)

TOL = dict(rtol=0, atol=1e-6)


def _ff_net(seed=0, policy=None):
    b = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
         .updater(Updater.ADAM))
    if policy:
        b = b.dtype_policy(policy)
    conf = (b.list()
            .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=12, n_out=3))
            .build())
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
            .updater(Updater.SGD).list()
            .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
            .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                       loss_function=LossFunction.MCXENT))
            .build())
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
         .updater(Updater.ADAM)
         .graph_builder()
         .add_inputs("in")
         .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                          activation="tanh"), "in")
         .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
         .set_outputs("out"))
    return ComputationGraph(g.build()).init()


def _ff_data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _rnn_data(n=48, t=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    lm = (np.arange(t)[None, :]
          < rng.integers(3, t + 1, n)[:, None]).astype(np.float32)
    return DataSet(x, y, None, lm)


def _lm(seed=1, heads=4, kv_heads=None):
    from deeplearning4j_tpu.models.transformer import TransformerLM

    lm = TransformerLM(vocab_size=50, d_model=16, num_heads=heads,
                      num_layers=2, d_ff=32, max_len=96,
                      pos_encoding="rope", seed=seed,
                      **({"num_kv_heads": kv_heads} if kv_heads else {}))
    lm._ensure_init()
    return lm


def _assert_params_close(a, b, **tol):
    fa = jax.tree_util.tree_leaves(jax.device_get(a))
    fb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32), **tol)


DPTP = MeshSpec(data=2, model=4)


# ---------------------------------------------------------------------------
# spec totality
# ---------------------------------------------------------------------------
class TestSpecTotality:
    @pytest.mark.parametrize("factory", [_ff_net, _rnn_net, _ff_graph])
    def test_every_network_leaf_mapped(self, factory):
        net = factory()
        reg = ShardingRegistry.for_network(net, build_mesh(DPTP))
        specs = reg.leaf_specs(net.params)
        leaves = jax.tree_util.tree_leaves(net.params)
        assert len(specs) == len(leaves)
        assert all(isinstance(s, P) for s in specs)
        # TP actually shards something (the Megatron column/gate splits)
        assert any(s != P() for s in specs)

    def test_transformer_leaves_mapped(self):
        lm = _lm()
        reg = ShardingRegistry.for_transformer(lm, build_mesh(DPTP))
        specs = reg.leaf_specs(lm.params)
        assert len(specs) == len(jax.tree_util.tree_leaves(lm.params))
        assert reg.spec_for("blocks", 0, "attn", "wq") == P(None, MODEL_AXIS)
        assert reg.spec_for("blocks", 0, "attn", "wo") == P(MODEL_AXIS, None)

    def test_unmapped_leaf_raises(self):
        """A param leaf the spec tree does not cover must raise, not
        silently replicate."""
        net = _ff_net()
        reg = ShardingRegistry.for_network(net, build_mesh(DPTP))
        grown = jax.device_get(net.params)
        grown["0"]["mystery"] = np.zeros((3, 3), np.float32)
        with pytest.raises(UnmappedLeafError):
            reg.leaf_specs(grown)
        with pytest.raises(UnmappedLeafError):
            reg.spec_for("0", "mystery")

    def test_spec_for_subtree_is_not_a_leaf(self):
        net = _ff_net()
        reg = ShardingRegistry.for_network(net, build_mesh(DPTP))
        with pytest.raises(UnmappedLeafError):
            reg.spec_for("0")

    def test_pure_dp_mesh_replicates_all_explicitly(self):
        net = _ff_net()
        reg = ShardingRegistry.for_network(net, build_mesh())
        assert all(s == P() for s in reg.leaf_specs(net.params))
        assert reg.declared_axes == {DATA_AXIS}

    def test_declared_axes_tp(self):
        net = _ff_net()
        reg = ShardingRegistry.for_network(net, build_mesh(DPTP))
        assert reg.declared_axes == {DATA_AXIS, MODEL_AXIS}
        d = reg.describe()
        assert d["mesh"] == {"data": 2, "model": 4}
        assert d["sharded_leaves"] > 0


# ---------------------------------------------------------------------------
# env-driven mesh resolution
# ---------------------------------------------------------------------------
class TestMeshFromEnv:
    def test_parse_shapes(self):
        assert parse_mesh_shape("8x1") == MeshSpec(data=8, model=1, pipe=1)
        assert parse_mesh_shape("4x2") == MeshSpec(data=4, model=2, pipe=1)
        assert parse_mesh_shape("2x2x2") == MeshSpec(data=2, model=2,
                                                     pipe=2)
        with pytest.raises(ValueError):
            parse_mesh_shape("2x2x2x2")
        with pytest.raises(ValueError):
            parse_mesh_shape("axb")

    def test_mesh_shape_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_MESH_SHAPE", "4x2")
        mesh = mesh_from_env()
        assert dict(mesh.shape) == {"data": 4, "model": 2}

    def test_tp_shards_env(self, monkeypatch):
        monkeypatch.delenv("DL4J_MESH_SHAPE", raising=False)
        monkeypatch.setenv("DL4J_TP_SHARDS", "4")
        mesh = mesh_from_env()
        assert dict(mesh.shape) == {"data": 2, "model": 4}

    def test_unset_is_none(self, monkeypatch):
        monkeypatch.delenv("DL4J_MESH_SHAPE", raising=False)
        monkeypatch.delenv("DL4J_TP_SHARDS", raising=False)
        assert mesh_from_env() is None

    def test_batch_spec_layouts(self):
        assert batch_spec(2) == P(DATA_AXIS, None)
        assert batch_spec(3, stacked=True) == P(None, DATA_AXIS, None)


# ---------------------------------------------------------------------------
# DP×TP fused epoch parity — one program, 1 dispatch/chunk, <=1e-6
# ---------------------------------------------------------------------------
def _fit_pair(factory, data_factory, batch, variant):
    kw = {}
    if variant == "accum":
        kw["accum_steps"] = 2
    kw["guard"] = "skip" if variant == "guard" else "off"
    if variant == "telemetry":
        kw["telemetry"] = True
    ref = factory(seed=5)
    it = ListDataSetIterator(data_factory(), batch)
    h0 = ref.fit_epochs(it, 3, **kw)
    tp = factory(seed=5)
    it = ListDataSetIterator(data_factory(), batch)
    tp._train_dispatches = 0
    h1 = tp.fit_epochs(it, 3, mesh=build_mesh(DPTP), **kw)
    return ref, tp, h0, h1


VARIANTS = ["plain", "accum", "guard", "telemetry"]


class TestDpTpFusedParity:
    @pytest.mark.parametrize("variant", VARIANTS)
    def test_ff(self, variant):
        ref, tp, h0, h1 = _fit_pair(_ff_net, _ff_data, 16, variant)
        assert tp._train_dispatches == 1  # ONE GSPMD program, all epochs
        _assert_params_close(ref.params, tp.params, **TOL)
        np.testing.assert_allclose(np.asarray(h0), np.asarray(h1), **TOL)

    @pytest.mark.parametrize("variant", VARIANTS)
    def test_rnn(self, variant):
        ref, tp, h0, h1 = _fit_pair(_rnn_net, _rnn_data, 8, variant)
        assert tp._train_dispatches == 1
        _assert_params_close(ref.params, tp.params, **TOL)
        np.testing.assert_allclose(np.asarray(h0), np.asarray(h1), **TOL)

    @pytest.mark.parametrize("variant", VARIANTS)
    def test_graph(self, variant):
        ref, tp, h0, h1 = _fit_pair(_ff_graph, _ff_data, 16, variant)
        assert tp._train_dispatches == 1
        _assert_params_close(ref.params, tp.params, **TOL)
        np.testing.assert_allclose(np.asarray(h0), np.asarray(h1), **TOL)

    def test_ff_mixed_bf16(self):
        """mixed_bf16 parity: sharded vs single-device, both under the
        bf16-step/f32-masters policy (the PR-14 grouped-updater fallback
        handles TP-sharded state). Tolerance is bf16-scaled, not 1e-6:
        TP reorders the row-parallel GEMM's bf16 partial-sum reduction,
        and bf16's epsilon (~7.8e-3) bounds the achievable agreement —
        the f32 variants above hold the 1e-6 contract."""
        ref = _ff_net(seed=5, policy="mixed_bf16")
        ref.fit_epochs(ListDataSetIterator(_ff_data(), 16), 3)
        tp = _ff_net(seed=5, policy="mixed_bf16")
        tp._train_dispatches = 0
        tp.fit_epochs(ListDataSetIterator(_ff_data(), 16), 3,
                      mesh=build_mesh(DPTP))
        assert tp._train_dispatches == 1
        _assert_params_close(ref.params, tp.params, rtol=0, atol=8e-3)

    def test_tp_params_actually_sharded(self):
        """The fused run leaves the column-split Dense W sharded over
        ``model`` — proof the program ran TP, not replicated DP."""
        tp = _ff_net(seed=5)
        tp.fit_epochs(ListDataSetIterator(_ff_data(), 16), 2,
                      mesh=build_mesh(DPTP))
        w = tp.params["0"]["W"]  # P(None, "model"): 12/4 cols per shard
        shapes = {s.data.shape for s in w.addressable_shards}
        assert shapes == {(6, 3)}
        assert tp._sharding_registry.spec_for("0", "W") == P(None,
                                                             MODEL_AXIS)


# ---------------------------------------------------------------------------
# topology reshard: 8×1 → 4×2 mid-run
# ---------------------------------------------------------------------------
class TestTopologyReshard:
    def _run(self, factory, plan):
        net = factory(seed=9)
        seen = {"n": 0}

        def on_chunk(done):
            seen["n"] += 1
            if seen["n"] in plan:
                net.request_reshard(plan[seen["n"]])
            return False

        net.fit_epochs(ListDataSetIterator(_ff_data(), 16), 6,
                       chunk_epochs=2, mesh=build_mesh(MeshSpec(data=8)),
                       on_chunk=on_chunk)
        return net

    @pytest.mark.parametrize("factory", [_ff_net, _ff_graph])
    def test_8x1_to_4x2_mid_run(self, factory):
        """DP-only 8×1 for the first chunk, then a TOPOLOGY change to
        4×2 (DP shrinks, TP appears): final params <= 1e-6 of the
        uninterrupted 8×1 run — the registry re-derives every spec from
        the new mesh and the host snapshot lands on it."""
        base = self._run(factory, plan={})
        resharded = self._run(
            factory, plan={1: build_mesh(MeshSpec(data=4, model=2))})
        _assert_params_close(base.params, resharded.params, **TOL)
        # post-reshard placement really is the 4×2 registry layout
        reg = resharded._sharding_registry
        assert dict(reg.mesh.shape) == {"data": 4, "model": 2}
        assert reg.declared_axes == {DATA_AXIS, MODEL_AXIS}

    def test_4x2_back_to_8x1(self):
        base = self._run(_ff_net, plan={})
        there_and_back = self._run(_ff_net, plan={
            1: build_mesh(MeshSpec(data=4, model=2)),
            2: build_mesh(MeshSpec(data=8)),
        })
        _assert_params_close(base.params, there_and_back.params, **TOL)


# ---------------------------------------------------------------------------
# TP serving: same registry, token-identical streams, per-shard budget
# ---------------------------------------------------------------------------
class TestTpServing:
    def _streams(self, srv, prompts, n=12):
        reqs = [srv.submit(p, n) for p in prompts]
        srv.drain()
        return [list(r.tokens) for r in reqs]

    def test_greedy_token_identity_and_budget(self):
        from deeplearning4j_tpu.monitor.memory import validate_cache_budget
        from deeplearning4j_tpu.serving.server import DecodeServer

        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 50, size=k).astype(np.int32)
                   for k in (5, 9)]
        base = self._streams(
            DecodeServer(_lm(seed=3), slots=2, max_len=64), prompts)
        srv = DecodeServer(_lm(seed=3), slots=2, max_len=64,
                           mesh=build_mesh(DPTP))
        assert self._streams(srv, prompts) == base
        cache = srv.engine.cache
        assert cache.n_shard == 4  # Hkv=4 heads tile the model axis
        # pool physically sharded: each device holds Hkv/tp heads
        shapes = {s.data.shape for s in cache.k.addressable_shards}
        assert {sh[3] for sh in shapes} == {1}
        info = validate_cache_budget(cache)
        assert info["within_tolerance"], info
        assert info["n_shard"] == 4
        assert srv.stats()["kv_shards"] == 4

    def test_registry_specs_shared_with_training_side(self):
        """Serving consumes the SAME registry class/specs ``param_specs``
        declares — not a parallel sharding path."""
        from deeplearning4j_tpu.serving.server import DecodeServer

        srv = DecodeServer(_lm(seed=3), slots=2, max_len=64,
                           mesh=build_mesh(DPTP))
        reg = srv.engine.registry
        assert isinstance(reg, ShardingRegistry)
        assert reg.spec_for("blocks", 0, "attn", "wq") == P(None,
                                                            MODEL_AXIS)
        assert reg.kv_pool_spec(4) == P(None, None, None, MODEL_AXIS, None)

    def test_gqa_fallback_replicates_pool(self):
        """kv heads that do not tile the model axis fall back to a
        replicated pool (matching the wk/wv param fallback) — loudly,
        never an in-head split."""
        from deeplearning4j_tpu.serving.server import DecodeServer

        srv = DecodeServer(_lm(seed=3, heads=4, kv_heads=1), slots=2,
                           max_len=64, mesh=build_mesh(DPTP))
        cache = srv.engine.cache
        assert cache.n_shard == 1
        shapes = {s.data.shape for s in cache.k.addressable_shards}
        assert len(shapes) == 1  # full copy everywhere

    def test_env_mesh_reaches_server(self, monkeypatch):
        from deeplearning4j_tpu.serving.server import DecodeServer

        monkeypatch.setenv("DL4J_MESH_SHAPE", "2x4")
        srv = DecodeServer(_lm(seed=3), slots=2, max_len=64)
        assert srv.engine.registry is not None
        assert dict(srv.engine.mesh.shape) == {"data": 2, "model": 4}


# ---------------------------------------------------------------------------
# contracts: declared axes from the registry + seeded violation
# ---------------------------------------------------------------------------
class TestRegistryContracts:
    def test_tp_programs_green_under_registry_axes(self):
        from deeplearning4j_tpu.analysis.contracts import (
            check_network_contracts)

        net = _ff_net(seed=5)
        cache = net.build_epoch_cache(
            ListDataSetIterator(_ff_data(), 16), mesh=build_mesh(DPTP))
        net.fit_epochs(cache, 2)
        # declared-axes auto-resolved from net._sharding_registry
        results = check_network_contracts(net, cache, epochs=2)
        assert all(not v for v in results.values())

    def test_seeded_sparse_collective_over_undeclared_axis(self):
        """The hardest case: a collective that only fires on one branch
        of a ``cond`` (sparse/uneven), over an axis the registry never
        declared. The checker must walk into the branch sub-jaxpr and
        flag it."""
        from deeplearning4j_tpu.analysis.contracts import (
            check_network_contracts)
        from deeplearning4j_tpu.compat import shard_map

        net = _ff_net(seed=5)
        mesh = build_mesh(DPTP)
        cache = net.build_epoch_cache(
            ListDataSetIterator(_ff_data(), 16), mesh=mesh)
        net.fit_epochs(cache, 2)
        key = next(iter(net._epoch_steps))
        good = net._epoch_steps[key]

        def rogue(params, upd, nst, it, lr, xs, ys, fms, lms, keys):
            out = good(params, upd, nst, it, lr, xs, ys, fms, lms, keys)

            def body(x):
                return jax.lax.cond(
                    jnp.sum(x) > 0,
                    lambda v: jax.lax.psum(v, MODEL_AXIS),
                    lambda v: v, x)

            leak = shard_map(body, mesh=mesh,
                             in_specs=P(DATA_AXIS, MODEL_AXIS),
                             out_specs=P(DATA_AXIS, MODEL_AXIS))(
                                 jnp.ones((2, 4), jnp.float32))
            return out[:3] + (out[3] + jnp.sum(leak) * 0.0,) + out[4:]

        # registry that declares ONLY data (explicit replicate-all)
        from deeplearning4j_tpu.parallel.sharding_registry import (
            _replicate_all_tree)

        dp_only = ShardingRegistry(
            mesh, _replicate_all_tree(jax.device_get(net.params)),
            name="dp-only")
        net._epoch_steps = {key: rogue}
        results = check_network_contracts(
            net, cache, epochs=2, registry=dp_only,
            raise_on_violation=False, expect_donation=False)
        flat = "\n".join(v for vs in results.values() for v in vs)
        assert "undeclared mesh axis 'model'" in flat
