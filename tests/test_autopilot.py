"""Always-on fleet: grant lease protocol, elastic mesh autopilot, chaos soak.

The contract under test (resilience/lease.py + resilience/autopilot.py +
the elastic reshard path through drive_epoch_chunks + the flight
recorder's ``reacquired`` end state):

- a :class:`GrantLease` bounds every acquisition attempt, releases and
  RE-ACQUIRES under escalating backoff on a wedge (``grant.reacquire``
  spans, ``grant.backoff`` booked as ``grant_wait`` badput), raises
  ``GrantWedgedError`` only on exhaustion, and leaves a
  ``grant.reacquired`` rescue record that flight classification reports
  as ``reacquired`` (clean-with-recovery) instead of wedged;
- ``net.request_reshard(mesh)`` applies at the NEXT chunk boundary via
  the in-process elastic reshard (device snapshot → respec → continue):
  final params within 1e-6 of the uninterrupted run, cursor/RNG/updater
  state carried exactly, the StepWatchdog deadline recomputed from the
  new chunk shape/device width;
- :class:`GoodputAutopilot` turns the PR-9 fleet gauges into
  evict/reshard/re-admit decisions — silence past the threshold and
  straggler STREAKS evict, goodput below ``DL4J_GOODPUT_FLOOR``
  reshards, a healthy returning member re-admits — every decision
  evidence-logged as an ``autopilot.decision`` event and routed through
  the same evidence paths the master tick uses
  (``DistributedTrainer.evict_worker`` → ``eviction_log``);
- the chaos soak: a bounded preempt + wedge + straggle + evict schedule
  across a multi-chunk fused run finishes with final params within 1e-6
  of the unfaulted run, the wedge re-acquired and booked as
  ``grant_wait``, and ledger goodput above the configured floor (the
  full-length soak rides the ``slow``+``chaos`` markers).
"""

import importlib.util
import logging
import os
import threading
import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.monitor.flight import (
    FlightRecorder,
    classify_end_state,
    set_flight,
)
from deeplearning4j_tpu.monitor.ledger import (
    RunLedger,
    run_ledger,
    set_run_ledger,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import ParallelWrapper, build_mesh
from deeplearning4j_tpu.parallel.cluster import FaultTolerantTrainer
from deeplearning4j_tpu.parallel.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.parallel.workrouter import DistributedTrainer
from deeplearning4j_tpu.perf.epoch_cache import (
    DeviceDataSetCache,
    chunk_deadline_s,
)
from deeplearning4j_tpu.resilience import (
    AutopilotDecision,
    GoodputAutopilot,
    GrantLease,
    GrantWedgedError,
    autopilot_enabled,
    fail_nth,
    fail_times,
    goodput_floor,
    inject,
)
from deeplearning4j_tpu.resilience.lease import (
    grant_lease_s,
    grant_reacquires,
)

TOL = dict(rtol=0, atol=1e-6)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


flight_report = _load_script("flight_report")


def _ff_net(seed=7):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=8, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _graph_net(seed=7):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=8,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=8, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build()).init()


def _ff_data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _assert_close(a, b, **tol):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x, np.float64),
                                   np.asarray(y, np.float64),
                                   **(tol or TOL))


def _new_spans(mark):
    return tracer().spans()[mark:]


# ---------------------------------------------------------------------------
# grant lease protocol
# ---------------------------------------------------------------------------


class TestGrantLease:
    def test_clean_first_attempt(self):
        lease = GrantLease("t", lambda: "ok", bounded=False,
                           sleep=lambda s: None)
        mark = len(tracer().spans())
        assert lease.acquire() == "ok"
        assert lease.state == "held"
        assert lease.reacquires == 0
        names = [s.name for s in _new_spans(mark)]
        assert "grant.acquire" in names
        assert "grant.reacquired" not in names
        assert "grant.reacquire" not in names

    def test_transient_failure_reacquires_with_escalating_backoff(self):
        sleeps = []
        calls = {"n": 0}

        def acq():
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError("wedged")
            return "grant"

        lease = GrantLease("t", acq, bounded=False, max_reacquires=3,
                           base_backoff_s=2.0, backoff_multiplier=2.0,
                           sleep=sleeps.append)
        mark = len(tracer().spans())
        assert lease.acquire() == "grant"
        assert lease.reacquires == 2
        # deterministic escalation: 2, then 4 (capped at max_backoff_s)
        assert sleeps == [2.0, 4.0]
        names = [s.name for s in _new_spans(mark)]
        assert names.count("grant.reacquire") == 2
        assert "grant.reacquired" in names
        assert "grant.backoff" in names

    def test_bounded_wedge_detected_without_exception(self):
        """A blocking acquisition that never raises is still detected:
        the daemon-thread bound turns silence into a wedge."""
        release_calls = []
        gate = threading.Event()
        calls = {"n": 0}

        def acq():
            calls["n"] += 1
            if calls["n"] == 1:
                gate.wait()  # wedge: blocks until the test releases it
            return "late-grant"

        lease = GrantLease("t", acq, bounded=True, lease_s=0.05,
                           max_reacquires=1,
                           release=lambda: release_calls.append(1),
                           sleep=lambda s: None)
        try:
            assert lease.acquire() == "late-grant"
            assert lease.reacquires == 1
            assert release_calls == [1]  # released before re-acquiring
        finally:
            gate.set()  # never leak a blocked thread

    def test_exhaustion_raises_grant_wedged(self):
        gate = threading.Event()
        lease = GrantLease("t", gate.wait, bounded=True,
                           lease_s=0.02, max_reacquires=1,
                           sleep=lambda s: None)
        try:
            with pytest.raises(GrantWedgedError) as ei:
                lease.acquire()
        finally:
            gate.set()
        assert ei.value.attempts == 2
        assert lease.state == "wedged"

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def acq():
            calls["n"] += 1
            raise ValueError("code bug, not a wedge")

        lease = GrantLease("t", acq, bounded=False,
                           retryable=(OSError,), sleep=lambda s: None)
        with pytest.raises(ValueError):
            lease.acquire()
        assert calls["n"] == 1  # no backoff budget burned

    def test_probe_gates_reacquire(self):
        """A failing re-probe consumes the cycle WITHOUT paying the full
        acquisition; a passing one proceeds to the real attempt."""
        probes = []
        acquires = {"n": 0}

        def probe():
            probes.append(1)
            return len(probes) >= 2  # first re-probe fails

        def acq():
            acquires["n"] += 1
            if acquires["n"] == 1:
                raise OSError("wedged")
            return "ok"

        lease = GrantLease("t", acq, bounded=False, probe=probe,
                           max_reacquires=3, sleep=lambda s: None)
        assert lease.acquire() == "ok"
        assert len(probes) == 2      # failed once, passed once
        assert acquires["n"] == 2    # the failed probe skipped acquire

    def test_fault_site_wedges_deterministically(self):
        """DL4J_FAULTS-style chaos: an injected grant.lease fault is a
        wedged attempt, re-acquired like a real one."""
        with inject("grant.lease", fail_times(1)):
            lease = GrantLease("t", lambda: "ok", bounded=False,
                               max_reacquires=2, sleep=lambda s: None)
            assert lease.acquire() == "ok"
        assert lease.reacquires == 1

    def test_fault_site_bypasses_narrow_retryable_filter(self):
        """The documented chaos contract holds on EVERY lease, including
        ones (bench probe/init, dryrun child) whose retryable filters
        name only their real failure types: an injected FaultInjected is
        always a wedge, never a crash."""
        class _OnlyThis(RuntimeError):
            pass

        with inject("grant.lease", fail_times(1)):
            lease = GrantLease("t", lambda: "ok", bounded=False,
                               max_reacquires=2, retryable=(_OnlyThis,),
                               sleep=lambda s: None)
            assert lease.acquire() == "ok"
        assert lease.reacquires == 1

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("DL4J_GRANT_LEASE_S", "17.5")
        monkeypatch.setenv("DL4J_GRANT_REACQUIRES", "4")
        assert grant_lease_s() == 17.5
        assert grant_reacquires() == 4
        monkeypatch.setenv("DL4J_GRANT_LEASE_S", "bogus")
        monkeypatch.setenv("DL4J_GRANT_REACQUIRES", "bogus")
        assert grant_lease_s() == 90.0
        assert grant_reacquires() == 2
        lease = GrantLease("t", lambda: 1)
        assert lease.lease_s == 90.0
        assert lease.max_reacquires == 2

    def test_ledger_books_reacquire_as_grant_wait(self):
        """The rescue costs ledger-booked grant_wait badput, not the
        round: backoff + reacquire spans classify under grant_wait."""
        ledger = RunLedger(span_source=lambda: tracer().spans())
        calls = {"n": 0}

        def acq():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("wedged")
            return "ok"

        lease = GrantLease("t", acq, bounded=False, max_reacquires=1,
                           base_backoff_s=0.05, sleep=time.sleep)
        assert lease.acquire() == "ok"
        rep = ledger.report()
        assert rep["states"]["grant_wait"] >= 0.04


# ---------------------------------------------------------------------------
# flight classification: reacquired = clean-with-recovery
# ---------------------------------------------------------------------------


class TestFlightReacquired:
    def _records(self, *, reacquired: bool, status="clean"):
        recs = [{"kind": "run.start", "t_wall": 1.0}]
        if reacquired:
            recs.append({"kind": "span", "name": "grant.reacquired",
                         "t_wall": 2.0, "attrs": {"attempts": 1}})
        recs += [{"kind": "chunk.done", "t_wall": 3.0},
                 {"kind": "run.end", "status": status, "t_wall": 4.0}]
        return recs

    def test_clean_with_rescue_classifies_reacquired(self):
        v = classify_end_state(self._records(reacquired=True))
        assert v["end_state"] == "reacquired"
        assert v["evidence"]["n_reacquires"] == 1

    def test_clean_without_rescue_stays_clean(self):
        v = classify_end_state(self._records(reacquired=False))
        assert v["end_state"] == "clean"

    def test_error_status_beats_reacquired(self):
        v = classify_end_state(
            self._records(reacquired=True, status="error:RuntimeError"))
        assert v["end_state"] == "crashed"

    def test_unclosed_run_with_wedge_evidence_still_wedged(self):
        recs = [{"kind": "run.start", "t_wall": 1.0},
                {"kind": "span", "name": "grant.watchdog", "t_wall": 2.0}]
        assert classify_end_state(recs)["end_state"] == "wedged"

    def test_recorder_round_trip_and_report_tool(self, tmp_path):
        """A real segment ring carrying a lease rescue classifies as
        reacquired through scripts/flight_report.py too."""
        fr = flight_report
        d = str(tmp_path / "flight")
        rec = FlightRecorder(d, heartbeat_s_=60.0)
        set_flight(rec)
        try:
            from deeplearning4j_tpu.monitor.ledger import (
                ledger_chunk_done, ledger_chunk_start, ledger_run_end,
                ledger_run_start)

            set_run_ledger(RunLedger())
            ledger_run_start(model="t", epochs=1)
            tracer().event("grant.reacquired", lease="t", attempts=2)
            ledger_chunk_start()
            ledger_chunk_done()
            ledger_run_end(status="clean")
        finally:
            set_flight(None)
            set_run_ledger(None)
            rec.close()
        report = fr.build_report(d)
        assert report["end_state"] == "reacquired"
        assert report["evidence"]["n_reacquires"] == 1


# ---------------------------------------------------------------------------
# mid-run elastic reshard
# ---------------------------------------------------------------------------


def _reshard_schedule(net, plan):
    """on_chunk callback issuing request_reshard per the {chunk: mesh}
    plan (the autopilot's actuator path, driven deterministically)."""
    seen = {"n": 0}

    def on_chunk(done):
        seen["n"] += 1
        if seen["n"] in plan:
            net.request_reshard(plan[seen["n"]])
        return False

    return on_chunk


class TestElasticReshard:
    def test_mln_grow_then_shrink_1e6(self):
        data = [_ff_data(8, seed=i) for i in range(4)]
        base = _ff_net()
        hist_a = base.fit_epochs(ListDataSetIterator(list(data), 8), 6,
                                 chunk_epochs=2)
        net = _ff_net()
        hist_b = net.fit_epochs(
            ListDataSetIterator(list(data), 8), 6, chunk_epochs=2,
            on_chunk=_reshard_schedule(
                net, {1: build_mesh(), 2: None}))
        _assert_close(base.params, net.params)
        _assert_close(base.updater_state, net.updater_state)
        np.testing.assert_allclose(np.asarray(hist_a),
                                   np.asarray(hist_b), **TOL)
        # cursor + RNG chain carried exactly through both reshards
        assert base.iteration_count == net.iteration_count
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(base._rng)),
            np.asarray(jax.device_get(net._rng)))

    def test_graph_reshard_1e6(self):
        data = [_ff_data(8, seed=i) for i in range(3)]
        base = _graph_net()
        base.fit_epochs(ListDataSetIterator(list(data), 8), 4,
                        chunk_epochs=1)
        net = _graph_net()
        net.fit_epochs(
            ListDataSetIterator(list(data), 8), 4, chunk_epochs=1,
            on_chunk=_reshard_schedule(net, {2: build_mesh()}))
        _assert_close(base.params, net.params)

    def test_reshard_starting_from_mesh(self):
        """A run LAUNCHED on the mesh shrinks to one device mid-run."""
        data = [_ff_data(8, seed=i) for i in range(4)]
        base = _ff_net()
        base.fit_epochs(ListDataSetIterator(list(data), 8), 4,
                        chunk_epochs=1)
        net = _ff_net()
        net.fit_epochs(
            ListDataSetIterator(list(data), 8), 4, chunk_epochs=1,
            mesh=build_mesh(),
            on_chunk=_reshard_schedule(net, {2: None}))
        _assert_close(base.params, net.params)

    def test_respec_values_bitwise_and_shard_accounting(self):
        cache = DeviceDataSetCache.build([_ff_data(16)])
        ref = np.asarray(cache.features)
        mesh = build_mesh()
        cache.respec(mesh)
        assert cache.mesh is mesh
        assert cache.n_shard == 8  # batch 16 tiles the 8-way data axis
        np.testing.assert_array_equal(np.asarray(cache.features), ref)
        cache.respec(None)
        assert cache.mesh is None and cache.n_shard == 1
        np.testing.assert_array_equal(np.asarray(cache.features), ref)

    def test_watchdog_deadline_rescaled_after_shrink(self, monkeypatch):
        """Satellite contract: after an 8→1 shrink the chunk deadline
        grows by the width factor — a legitimately slower chunk is not
        flagged as a stall."""
        monkeypatch.setenv("DL4J_STEP_DEADLINE_S", "10")
        data = [_ff_data(8, seed=i) for i in range(2)]
        net = _ff_net()
        net.fit_epochs(
            ListDataSetIterator(list(data), 8), 3, chunk_epochs=1,
            mesh=build_mesh(),
            on_chunk=_reshard_schedule(net, {1: None}))
        # per-step 10 s x (1 epoch x 2 batches) x width factor 8
        assert net._chunk_watchdog.deadline_s == pytest.approx(160.0)

    def test_chunk_deadline_width_factor(self, monkeypatch):
        monkeypatch.setenv("DL4J_STEP_DEADLINE_S", "2")
        assert chunk_deadline_s(10) == pytest.approx(20.0)
        assert chunk_deadline_s(10, width_factor=4) == pytest.approx(80.0)
        # growth never TIGHTENS the deadline
        assert chunk_deadline_s(10, width_factor=0.25) == pytest.approx(
            20.0)
        monkeypatch.delenv("DL4J_STEP_DEADLINE_S")
        assert chunk_deadline_s(1, width_factor=8) == pytest.approx(240.0)

    def test_wrapper_path_applies_request(self):
        """ParallelWrapper honors the reshard request at the chunk
        boundary: its per-mesh epoch programs are dropped and re-pinned
        on the new mesh (pre-fix, the wrapper path logged a warning and
        DROPPED the request, training on the stale mesh)."""
        data = [_ff_data(16, seed=i) for i in range(2)]
        net = _ff_net()
        wrapper = ParallelWrapper(net, mesh=build_mesh())
        net.request_reshard(None)
        before = metrics().counter("elastic_reshards_total").value(
            model="MultiLayerNetwork")
        wrapper.fit_epochs(ListDataSetIterator(list(data), 16), 2,
                           chunk_epochs=1)
        assert net._pending_mesh is None
        assert metrics().counter("elastic_reshards_total").value(
            model="MultiLayerNetwork") == before + 1
        assert wrapper.mesh.shape["data"] == 1  # shrunk to one device

    def test_reshard_span_and_counter_on_timeline(self):
        data = [_ff_data(8, seed=i) for i in range(2)]
        net = _ff_net()
        mark = len(tracer().spans())
        net.fit_epochs(ListDataSetIterator(list(data), 8), 3,
                       chunk_epochs=1,
                       on_chunk=_reshard_schedule(net, {1: build_mesh()}))
        spans = [s for s in _new_spans(mark) if s.name == "reshard.elastic"]
        assert len(spans) == 1
        assert spans[0].attrs["n_shard"] == 8


# ---------------------------------------------------------------------------
# goodput autopilot
# ---------------------------------------------------------------------------


class TestAutopilot:
    def test_silence_evicts_with_evidence(self):
        acted = []
        ap = GoodputAutopilot(floor=0.0, silence_s=5.0,
                              clock=lambda: 100.0,
                              evict=lambda w, d: acted.append(w))
        mark = len(tracer().spans())
        out = ap.observe({"w0": {"step_s": 1.0}},
                         last_beat={"w0": 99.0, "w1": 80.0})
        assert [(d.action, d.target) for d in out] == [("evict", "w1")]
        assert out[0].reason == "heartbeat_silence"
        assert out[0].gauges["silent_s"] == pytest.approx(20.0)
        assert out[0].acted
        assert acted == ["w1"]
        evs = [s for s in _new_spans(mark)
               if s.name == "autopilot.decision"]
        assert len(evs) == 1
        assert evs[0].attrs["action"] == "evict"
        assert evs[0].attrs["silent_s"] == pytest.approx(20.0)

    def test_straggler_needs_a_streak(self):
        acted = []
        ap = GoodputAutopilot(floor=0.0, silence_s=1e9,
                              straggler_ticks=2, clock=lambda: 0.0,
                              evict=lambda w, d: acted.append(w))
        fleet = {"w0": {"step_s": 9.0}}
        assert ap.observe(fleet, stragglers=["w0"]) == []
        out = ap.observe(fleet, stragglers=["w0"])
        assert [(d.action, d.target, d.reason) for d in out] == [
            ("evict", "w0", "straggler_streak")]
        assert acted == ["w0"]

    def test_straggler_streak_resets_on_recovery(self):
        ap = GoodputAutopilot(floor=0.0, silence_s=1e9,
                              straggler_ticks=2, clock=lambda: 0.0)
        assert ap.observe({}, stragglers=["w0"]) == []
        assert ap.observe({}, stragglers=[]) == []      # recovered
        assert ap.observe({}, stragglers=["w0"]) == []  # streak restarted

    def test_goodput_floor_reshard_and_cooldown(self):
        resharded = []
        t = {"now": 0.0}
        ap = GoodputAutopilot(floor=50.0, silence_s=1e9, cooldown_s=10.0,
                              clock=lambda: t["now"],
                              reshard=lambda h, d: resharded.append(
                                  tuple(h)))
        fleet = {"w0": {"goodput_pct": 20.0}, "w1": {"goodput_pct": 90.0}}
        out = ap.observe(fleet, stragglers=["w1"])
        assert [d.action for d in out] == ["reshard"]
        assert out[0].gauges["goodput_pct"] == 20.0  # fleet min
        assert resharded == [("w0",)]  # straggler excluded from healthy
        t["now"] = 5.0
        assert ap.observe(fleet) == []  # cooling down
        t["now"] = 11.0
        assert [d.action for d in ap.observe(fleet)] == ["reshard"]

    def test_readmit_after_healthy_return(self):
        readmits = []
        t = {"now": 100.0}
        ap = GoodputAutopilot(floor=0.0, silence_s=5.0,
                              clock=lambda: t["now"],
                              readmit=lambda w, d: readmits.append(w))
        ap.observe({}, last_beat={"w0": 10.0})  # silent -> evicted
        assert ap.evicted == {"w0"}
        t["now"] = 200.0
        out = ap.observe({"w0": {"step_s": 1.0}},
                         last_beat={"w0": 199.0})
        assert [(d.action, d.target) for d in out] == [("readmit", "w0")]
        assert ap.evicted == set()
        assert readmits == ["w0"]

    def test_actuator_failure_marks_not_acted(self):
        def boom(w, d):
            raise RuntimeError("actuator down")

        ap = GoodputAutopilot(floor=0.0, silence_s=1.0,
                              clock=lambda: 100.0, evict=boom)
        out = ap.observe({}, last_beat={"w0": 0.0})
        assert len(out) == 1 and not out[0].acted

    def test_failed_eviction_not_latched_and_retried(self):
        """A bound evict actuator that RAISES leaves the member
        un-latched: the next tick retries instead of permanently
        forgetting a still-wedged worker over one tracker hiccup."""
        calls = {"n": 0}

        def flaky(w, d):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("tracker hiccup")

        ap = GoodputAutopilot(floor=0.0, silence_s=1.0,
                              clock=lambda: 100.0, evict=flaky)
        out1 = ap.observe({}, last_beat={"w0": 0.0})
        assert not out1[0].acted and ap.evicted == set()
        out2 = ap.observe({}, last_beat={"w0": 0.0})
        assert out2[0].acted and ap.evicted == {"w0"}

    def test_straggler_eviction_not_readmitted_same_pass(self):
        """The beat snapshot that justified a straggler eviction still
        carries that member's fresh beat — readmit must require a beat
        NEWER than the eviction, or every straggler eviction would be
        instantly contradicted."""
        t = {"now": 100.0}
        ap = GoodputAutopilot(floor=0.0, silence_s=1e9,
                              straggler_ticks=1,
                              clock=lambda: t["now"],
                              evict=lambda w, d: None)
        fleet = {"w0": {"step_s": 9.0}}
        out = ap.observe(fleet, stragglers=["w0"],
                         last_beat={"w0": 99.5})
        assert [d.action for d in out] == ["evict"]
        assert ap.evicted == {"w0"}
        # same stale beat next tick: still no readmit
        out = ap.observe(fleet, stragglers=[], last_beat={"w0": 99.5})
        assert out == []
        # a genuinely NEW beat after the eviction readmits
        t["now"] = 110.0
        out = ap.observe(fleet, stragglers=[], last_beat={"w0": 109.0})
        assert [d.action for d in out] == ["readmit"]

    def test_decision_counter_labeled_by_action(self):
        ap = GoodputAutopilot(floor=0.0, silence_s=1.0,
                              clock=lambda: 100.0)
        before = metrics().counter("autopilot_decisions_total").value(
            action="evict")
        ap.observe({}, last_beat={"w0": 0.0})
        assert metrics().counter("autopilot_decisions_total").value(
            action="evict") == before + 1

    def test_env_helpers(self, monkeypatch):
        monkeypatch.setenv("DL4J_AUTOPILOT", "1")
        assert autopilot_enabled()
        monkeypatch.setenv("DL4J_AUTOPILOT", "off")
        assert not autopilot_enabled()
        monkeypatch.setenv("DL4J_GOODPUT_FLOOR", "72.5")
        assert goodput_floor() == 72.5
        monkeypatch.setenv("DL4J_GOODPUT_FLOOR", "junk")
        assert goodput_floor() == 50.0


class TestTrainerIntegration:
    def _trainer(self, tracker, silence_s=0.05):
        from deeplearning4j_tpu.parallel.workrouter import (
            IterativeReduceWorkRouter)

        ap = GoodputAutopilot(floor=0.0, silence_s=silence_s)
        return DistributedTrainer(
            tracker, IterativeReduceWorkRouter(tracker),
            performer_factory=lambda: None, num_workers=1,
            autopilot=ap)

    def test_autopilot_evicts_through_evidence_logged_path(self):
        tracker = InMemoryStateTracker()
        trainer = self._trainer(tracker)
        tracker.heartbeat("w0", metrics={"step_s": 2.5})
        time.sleep(0.08)
        trainer.autopilot_tick(trainer.fleet_tick())
        assert trainer.evicted == ["w0"]
        assert "w0" not in tracker.workers()
        assert len(trainer.eviction_log) == 1
        entry = trainer.eviction_log[0]
        assert entry["reason"] == "autopilot:heartbeat_silence"
        assert entry["silent_s"] is not None
        assert entry["last_metrics"]["step_s"] == 2.5

    def test_evict_worker_requeues_claimed_jobs(self):
        tracker = InMemoryStateTracker()
        trainer = self._trainer(tracker)
        tracker.add_job({"x": 1})
        tracker.heartbeat("w0")
        job = tracker.claim_job("w0")
        assert job is not None
        trainer.evict_worker("w0", reason="test")
        assert tracker.jobs(status="pending")
        assert not tracker.jobs(status="claimed")

    def test_evict_worker_stops_loop_and_monitor(self):
        """A targeted eviction stops the worker FOR REAL — loop stop
        event set, heartbeat monitor stopped — or the still-running
        straggler would re-register on its next beat and the fleet
        would flap evict/readmit forever."""
        tracker = InMemoryStateTracker()
        trainer = self._trainer(tracker)
        wstop = threading.Event()
        trainer._worker_stops["w0"] = wstop

        class _Mon:
            stopped = False

            def stop(self):
                self.stopped = True

        mon = _Mon()
        trainer.monitors["w0"] = mon
        tracker.heartbeat("w0")
        trainer.evict_worker("w0", reason="test")
        assert wstop.is_set()
        assert mon.stopped
        assert "w0" not in tracker.workers()

    def test_file_tracker_evict_worker(self, tmp_path):
        from deeplearning4j_tpu.parallel.statetracker import (
            FileStateTracker)

        tracker = FileStateTracker(str(tmp_path))
        tracker.add_job({"x": 1})
        tracker.heartbeat("w0")
        tracker.claim_job("w0")
        assert tracker.evict_worker("w0")
        assert "w0" not in tracker.workers()
        assert tracker.jobs(status="pending")

    def test_replica_lease_wedge_marks_dead(self):
        """A serve replica whose grant lease exhausts is DEAD (the
        controller's crash path evicts + fails over), never a silent
        wedge holding the fleet."""
        from deeplearning4j_tpu.serving.fleet.replica import ServeReplica

        class _FakeServer:
            pass

        gate = threading.Event()
        lease = GrantLease("replica", gate.wait,
                           bounded=True, lease_s=0.02, max_reacquires=0,
                           sleep=lambda s: None)
        rep = ServeReplica("r0", model=None, server=_FakeServer(),
                           lease=lease)
        try:
            rep.start()
        finally:
            gate.set()
        assert rep.dead
        assert rep.dead_reason.startswith("grant wedged")


# ---------------------------------------------------------------------------
# the chaos soak: preempt + wedge + straggle + evict across a fused run
# ---------------------------------------------------------------------------


def _run_chaos_soak(tmp_path, *, epochs, n_batches, straggle_ms):
    """One soak round. Schedule, all deterministic:

    - chunk 2: PREEMPT (injected ``preempt.chunk`` latch) — checkpoint
      and stop; the relaunch re-acquires its backend grant through a
      lease whose FIRST attempt WEDGES (injected ``grant.lease`` fault)
      and is rescued (booked grant_wait, ``grant.reacquired`` on the
      timeline);
    - every chunk: STRAGGLE (injected ``epoch.chunk`` delay);
    - mid-relaunch: the autopilot sees a straggler fleet member and a
      goodput collapse, EVICTS the member through the trainer's
      evidence-logged path and RESHARDS the run onto the 8-device mesh
      via ``request_reshard`` (the elastic path) at the next chunk
      boundary.

    Returns (baseline_net, soaked_net, lease, trainer, decisions).
    """
    from deeplearning4j_tpu.resilience.faults import delay

    data = [_ff_data(8, seed=i) for i in range(n_batches)]

    baseline = _ff_net()
    baseline.fit_epochs(ListDataSetIterator(list(data), 8), epochs,
                        chunk_epochs=1)

    # --- incarnation 1: straggling chunks, preempted at chunk 2
    net = _ff_net()
    trainer = FaultTolerantTrainer(net, str(tmp_path))
    with inject("preempt.chunk", fail_nth(2)), \
            inject("epoch.chunk", delay(straggle_ms)):
        trainer.fit_epochs(ListDataSetIterator(list(data), 8), epochs,
                           chunk_epochs=1)
    assert trainer.preempted
    assert net._epoch_cursor == 2

    # --- relaunch: the backend grant wedges once and the lease rescues
    lease = GrantLease("soak.backend", lambda: "grant", bounded=False,
                       max_reacquires=2, base_backoff_s=0.05,
                       sleep=time.sleep)
    with inject("grant.lease", fail_times(1)):
        assert lease.acquire() == "grant"
    assert lease.reacquires == 1

    net2 = _ff_net()
    trainer2 = FaultTolerantTrainer(net2, str(tmp_path))
    assert trainer2.resume()

    # --- the autopilot acts on the fleet evidence: evict the wedged
    # member through the trainer-style evidence path, reshard the run
    tracker = InMemoryStateTracker()
    from deeplearning4j_tpu.parallel.workrouter import (
        IterativeReduceWorkRouter)

    dt = DistributedTrainer(tracker, IterativeReduceWorkRouter(tracker),
                            performer_factory=lambda: None,
                            num_workers=1)
    ap = GoodputAutopilot(
        floor=goodput_floor(), silence_s=0.05,
        evict=lambda w, d: dt.evict_worker(w, decision=d),
        reshard=lambda healthy, d: net2.request_reshard(build_mesh()))
    tracker.heartbeat("worker-1", metrics={"step_s": 9.0,
                                           "goodput_pct": 5.0})
    time.sleep(0.08)
    decisions = ap.observe(
        {"worker-1": {"step_s": 9.0, "goodput_pct": 5.0}},
        last_beat={w: tracker.last_heartbeat(w)
                   for w in tracker.workers()})
    assert {d.action for d in decisions} == {"evict", "reshard"}

    with inject("epoch.chunk", delay(straggle_ms)):
        trainer2.fit_epochs(ListDataSetIterator(list(data), 8), epochs,
                            chunk_epochs=1)
    assert not trainer2.preempted
    return baseline, net2, lease, dt, decisions


@pytest.mark.chaos
class TestChaosSoak:
    def test_bounded_soak_1e6_goodput_and_evidence(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("DL4J_GOODPUT_FLOOR", "20")
        set_run_ledger(RunLedger())
        mark = len(tracer().spans())
        try:
            baseline, soaked, lease, dt, decisions = _run_chaos_soak(
                tmp_path, epochs=5, n_batches=3, straggle_ms=5)
        finally:
            ledger = run_ledger()
            report = ledger.report()
            set_run_ledger(None)

        # final state within 1e-6 of the unfaulted run (preempt+resume
        # is bitwise; the mid-run reshard is 1e-6 by contract)
        _assert_close(baseline.params, soaked.params)
        _assert_close(baseline.updater_state, soaked.updater_state)
        assert baseline.iteration_count == soaked.iteration_count

        # the wedge was re-acquired and booked as grant_wait badput
        assert lease.reacquires == 1
        assert report["states"]["grant_wait"] >= 0.04
        spans = _new_spans(mark)
        assert any(s.name == "grant.reacquired" for s in spans)

        # ledger-proven goodput above the configured floor; the runs
        # themselves closed clean/stopped, never error
        assert report["goodput_pct"] is not None
        assert report["goodput_pct"] >= 20.0
        statuses = {r["status"] for r in report["runs"]}
        assert statuses <= {"clean", "stopped"}
        # straggle badput was observed (the injected chunk delays ran
        # inside run windows, keeping goodput below 100)
        assert report["goodput_pct"] < 100.0

        # every autopilot decision evidence-logged: the decision events
        # carry the triggering gauges, and the eviction went through the
        # master-tick evidence path
        dec_events = [s for s in spans if s.name == "autopilot.decision"]
        assert len(dec_events) == len(decisions) == 2
        assert all("reason" in s.attrs for s in dec_events)
        evict = [d for d in decisions if d.action == "evict"][0]
        assert evict.gauges["goodput_pct"] == 5.0
        assert len(dt.eviction_log) == 1
        assert dt.eviction_log[0]["reason"] == (
            "autopilot:heartbeat_silence")
        # the reshard decision was APPLIED through the elastic path
        assert any(s.name == "reshard.elastic" for s in spans)

    @pytest.mark.slow
    def test_full_length_soak(self, tmp_path, monkeypatch):
        """The long form: three preempt/resume incarnations, a wedge +
        rescue before each relaunch, straggling chunks throughout, a
        grow AND a shrink reshard — still 1e-6 and above the floor."""
        from deeplearning4j_tpu.resilience.faults import delay

        monkeypatch.setenv("DL4J_GOODPUT_FLOOR", "20")
        epochs, n_batches = 12, 4
        data = [_ff_data(8, seed=i) for i in range(n_batches)]
        baseline = _ff_net()
        baseline.fit_epochs(ListDataSetIterator(list(data), 8), epochs,
                            chunk_epochs=1)

        set_run_ledger(RunLedger())
        try:
            net = _ff_net()
            trainer = FaultTolerantTrainer(net, str(tmp_path))
            with inject("preempt.chunk", fail_nth(3)), \
                    inject("epoch.chunk", delay(10)):
                trainer.fit_epochs(ListDataSetIterator(list(data), 8),
                                   epochs, chunk_epochs=1)
            assert trainer.preempted
            total_reacquires = 0
            meshes = [build_mesh(), None, build_mesh(
                devices=jax.devices()[:4])]
            for round_i, mesh in enumerate(meshes):
                lease = GrantLease(f"soak.r{round_i}", lambda: "ok",
                                   bounded=False, max_reacquires=2,
                                   base_backoff_s=0.02, sleep=time.sleep)
                with inject("grant.lease", fail_times(1)):
                    lease.acquire()
                total_reacquires += lease.reacquires
                net = _ff_net()
                trainer = FaultTolerantTrainer(net, str(tmp_path))
                assert trainer.resume()
                net.request_reshard(mesh)
                preempt_at = 3 if round_i < len(meshes) - 1 else 10 ** 6
                with inject("preempt.chunk", fail_nth(preempt_at)), \
                        inject("epoch.chunk", delay(10)):
                    trainer.fit_epochs(
                        ListDataSetIterator(list(data), 8), epochs,
                        chunk_epochs=1)
                if round_i < len(meshes) - 1:
                    assert trainer.preempted
            assert not trainer.preempted
            report = run_ledger().report()
        finally:
            set_run_ledger(None)

        _assert_close(baseline.params, net.params)
        assert baseline.iteration_count == net.iteration_count
        assert total_reacquires == len(meshes)
        assert report["goodput_pct"] is not None
        assert report["goodput_pct"] >= 20.0
        assert report["states"]["grant_wait"] > 0
