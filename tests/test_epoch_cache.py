"""Epoch-pipeline tests: HBM dataset cache + whole-epoch scan fusion.

The contract under test (perf/epoch_cache.py + fit_epochs on both network
classes): the fused E-epochs x N-batches program must be OBSERVATIONALLY
identical to the per-step train loop fed the identical RNG key stream —
bitwise, not approximately — while making one train-program dispatch per
chunk instead of one per batch; over-budget datasets must silently take the
streaming path with identical results; and the fused program must compile
once per (bucket shape, chunk length), never once per call.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.perf.epoch_cache import (
    DeviceDataSetCache,
    DeviceMultiDataSetCache,
    epoch_schedule,
)


def _ff_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
        .updater(Updater.SGD).list()
        .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build())


def _ff_data(n=100, seed=0, label_mask=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    lm = (rng.integers(0, 2, n).astype(np.float32)
          if label_mask else None)
    return DataSet(x, y, None, lm)


def _rnn_data(n=24, t=7, seed=0, label_mask=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    lm = None
    if label_mask:
        # variable-length sequences: mask out tails
        lm = (np.arange(t)[None, :]
              < rng.integers(3, t + 1, n)[:, None]).astype(np.float32)
    return DataSet(x, y, None, lm)


def _reference_epochs_mln(net, cache, epochs, shuffle=True):
    """The per-step train program (the exact jitted step ``fit`` uses)
    driven host-side on the fused path's RNG stream: chunk keys split off
    ``net._rng`` the same way, each epoch key expanded through
    ``epoch_schedule`` eagerly. This IS the per-step fit loop on identical
    keys — the comparison the bitwise suite is named for."""
    keys = jax.random.split(net._rng, epochs + 1)
    net._rng = keys[0]
    it = net.iteration_count
    history = []
    for ekey in keys[1:]:
        order, skeys = epoch_schedule(ekey, cache.n_batches, shuffle)
        order = np.asarray(order)
        row = []
        for j in range(cache.n_batches):
            i = int(order[j])
            (net.params, net.updater_state, net.net_state, _, loss) = (
                net._train_step(
                    net.params, net.updater_state, net.net_state,
                    jnp.asarray(it, jnp.int32),
                    jnp.asarray(net._lr_scale_host, jnp.float32),
                    cache.features[i], cache.labels[i],
                    None if cache.features_mask is None
                    else cache.features_mask[i],
                    cache.labels_mask[i], skeys[j], None))
            it += 1
            row.append(np.asarray(loss))
        history.append(row)
    net.iteration_count = it
    return np.asarray(history)


def _reference_epochs_graph(net, cache, epochs, shuffle=True):
    keys = jax.random.split(net._rng, epochs + 1)
    net._rng = keys[0]
    it = net.iteration_count
    history = []
    for ekey in keys[1:]:
        order, skeys = epoch_schedule(ekey, cache.n_batches, shuffle)
        order = np.asarray(order)
        row = []
        for j in range(cache.n_batches):
            i = int(order[j])
            (net.params, net.updater_state, net.net_state, loss, _) = (
                net._train_step(
                    net.params, net.updater_state, net.net_state,
                    jnp.asarray(it, jnp.int32),
                    tuple(x[i] for x in cache.features),
                    tuple(y[i] for y in cache.labels),
                    None if cache.features_masks is None
                    else tuple(m[i] for m in cache.features_masks),
                    tuple(m[i] for m in cache.labels_masks),
                    skeys[j], None))
            it += 1
            row.append(np.asarray(loss))
        history.append(row)
    net.iteration_count = it
    return np.asarray(history)


class TestDeviceDataSetCache:
    def test_stacks_pads_and_counts(self):
        # 100 @ batch 32 → 32/32/32/4, one uniform bucket of 32
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(100), batch_size=32))
        assert cache is not None
        assert cache.n_batches == 4
        assert cache.batch == 32
        assert cache.total_examples == 100
        assert cache.features.shape == (4, 32, 6)
        assert cache.labels.shape == (4, 32, 3)
        # pad rows of the 4-row tail are masked out; real rows masked in
        lm = np.asarray(cache.labels_mask)
        assert lm.shape == (4, 32)
        np.testing.assert_array_equal(lm[3, :4], 1.0)
        np.testing.assert_array_equal(lm[3, 4:], 0.0)
        np.testing.assert_array_equal(lm[0], 1.0)

    def test_ragged_batches_share_max_bucket(self):
        # 70 @ batch 48 → 48/22 → buckets 64/32 → one uniform 64 stack
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(70), batch_size=48))
        assert cache.batch == 64
        assert cache.features.shape == (2, 64, 6)

    def test_over_budget_returns_none_and_resets_iterator(self):
        it = ListDataSetIterator(_ff_data(4096, seed=1), batch_size=512)
        assert DeviceDataSetCache.build(it, budget_mb=0.01) is None
        # the iterator is handed back ready for the streaming path
        assert len(list(it)) == 8

    def test_env_budget_zero_disables(self, monkeypatch):
        monkeypatch.setenv("DL4J_DEVICE_CACHE_MB", "0")
        assert DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(), batch_size=32)) is None

    def test_unstackable_shapes_return_none(self):
        rng = np.random.default_rng(0)
        batches = [DataSet(rng.normal(size=(8, 6)).astype(np.float32),
                           np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]),
                   DataSet(rng.normal(size=(8, 5)).astype(np.float32),
                           np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])]
        assert DeviceDataSetCache.build(batches) is None

    def test_missing_labels_return_none(self):
        assert DeviceDataSetCache.build(
            [DataSet(np.zeros((8, 6), np.float32))]) is None

    def test_multi_cache_promotes_datasets(self):
        cache = DeviceMultiDataSetCache.build(
            ListDataSetIterator(_ff_data(100), batch_size=32))
        assert cache is not None
        assert cache.n_batches == 4
        assert cache.features[0].shape == (4, 32, 6)
        assert cache.labels_masks[0].shape == (4, 32)


class TestBitwiseEquivalenceMLN:
    """fit_epochs vs the per-step train loop on identical RNG key streams
    — bitwise (rtol=0, atol=0), FF and RNN, with and without label masks."""

    @pytest.mark.parametrize("label_mask", [False, True])
    def test_ff(self, label_mask):
        data = _ff_data(100, label_mask=label_mask)
        fused, ref = _ff_net(), _ff_net()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(data, batch_size=32))
        hist = fused.fit_epochs(cache, 3)
        ref_hist = _reference_epochs_mln(ref, cache, 3)
        np.testing.assert_array_equal(np.asarray(hist), ref_hist)
        np.testing.assert_array_equal(fused.get_flat_params(),
                                      ref.get_flat_params())
        assert fused.iteration_count == ref.iteration_count == 12

    @pytest.mark.parametrize("label_mask", [False, True])
    def test_rnn(self, label_mask):
        data = _rnn_data(15, t=5, label_mask=label_mask)
        fused, ref = _rnn_net(), _rnn_net()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(data, batch_size=6))  # 6/6/3 → bucket 8
        assert cache.batch == 8
        hist = fused.fit_epochs(cache, 2)
        ref_hist = _reference_epochs_mln(ref, cache, 2)
        np.testing.assert_array_equal(np.asarray(hist), ref_hist)
        np.testing.assert_array_equal(fused.get_flat_params(),
                                      ref.get_flat_params())

    def test_no_shuffle_preserves_batch_order(self):
        data = _ff_data(96)
        fused, ref = _ff_net(), _ff_net()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(data, batch_size=32))
        hist = fused.fit_epochs(cache, 2, shuffle=False)
        ref_hist = _reference_epochs_mln(ref, cache, 2, shuffle=False)
        np.testing.assert_array_equal(np.asarray(hist), ref_hist)
        np.testing.assert_array_equal(fused.get_flat_params(),
                                      ref.get_flat_params())


class TestBitwiseEquivalenceGraph:
    @pytest.mark.parametrize("label_mask", [False, True])
    def test_ff_graph(self, label_mask):
        data = _ff_data(100, label_mask=label_mask)
        fused, ref = _ff_graph(), _ff_graph()
        fused.init(), ref.init()
        cache = DeviceMultiDataSetCache.build(
            ListDataSetIterator(data, batch_size=32))
        hist = fused.fit_epochs(cache, 3)
        ref_hist = _reference_epochs_graph(ref, cache, 3)
        np.testing.assert_array_equal(np.asarray(hist), ref_hist)
        for k, v in ref.get_param_table().items():
            np.testing.assert_array_equal(fused.get_param_table()[k], v)
        assert fused.iteration_count == ref.iteration_count == 12


class TestDispatchAndChunking:
    def test_one_dispatch_per_run_without_listeners(self):
        net = _ff_net()
        hist = net.fit_epochs(ListDataSetIterator(_ff_data(), 32), 5)
        assert net._train_dispatches == 1  # E epochs x N batches, one launch
        assert hist.shape == (5, 4)
        assert net.iteration_count == 20

    def test_listeners_get_per_epoch_decision_points(self):
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresIterationListener)

        net = _ff_net()
        lst = CollectScoresIterationListener()
        net.set_listeners(lst)
        hist = net.fit_epochs(ListDataSetIterator(_ff_data(), 32), 3)
        # default chunk with listeners = 1 epoch → one chunk_done per
        # epoch; the listener reconstructs EVERY step's (iteration,
        # loss) from the chunk history (PR-6 fused listener protocol)
        assert [it for it, _ in lst.scores] == list(range(1, 13))
        np.testing.assert_allclose(
            [s for _, s in lst.scores], np.asarray(hist).reshape(-1),
            rtol=1e-6)
        assert net._train_dispatches == 3

    def test_explicit_chunking_concatenates_history(self):
        net = _ff_net()
        hist = net.fit_epochs(ListDataSetIterator(_ff_data(96), 32), 4,
                              chunk_epochs=2)
        assert hist.shape == (4, 3)
        assert net._train_dispatches == 2

    def test_recompile_guard_one_miss_per_bucket_shape(self):
        """One jit cache miss per (bucket shape, chunk length) — a second
        run over the same-shaped cache must NOT recompile; a new bucket
        shape must add exactly one entry."""
        net = _ff_net()
        net.fit_epochs(ListDataSetIterator(_ff_data(100, seed=0), 32), 2)
        step = net._epoch_steps[(True, 1, True, 0)]
        assert step._cache_size() == 1
        net.fit_epochs(ListDataSetIterator(_ff_data(100, seed=7), 32), 2)
        assert step._cache_size() == 1  # same shapes: no new compile
        net.fit_epochs(ListDataSetIterator(_ff_data(200, seed=7), 64), 2)
        assert step._cache_size() == 2  # new bucket (64): exactly one more


class TestBudgetFallback:
    def test_oversized_dataset_streams_with_identical_results(self):
        """The HBM-budget fallback is silent and exact: a dataset over
        DL4J_DEVICE_CACHE_MB takes the async streaming path and produces
        the same parameters as the plain per-step fit loop."""
        data = _ff_data(128, seed=3)
        a, b = _ff_net(), _ff_net()
        hist = a.fit_epochs(ListDataSetIterator(data, 32), 2,
                            cache_mb=1e-4)  # ~100 KB dataset over budget
        assert hist is None  # fallback ran — no fused history
        for _ in range(2):
            b.fit(ListDataSetIterator(data, 32))
        np.testing.assert_array_equal(a.get_flat_params(),
                                      b.get_flat_params())
        assert a.iteration_count == b.iteration_count == 8

    def test_graph_fallback_matches_plain_fit(self):
        data = _ff_data(64, seed=4)
        a, b = _ff_graph().init(), _ff_graph().init()
        hist = a.fit_epochs(ListDataSetIterator(data, 32), 2, cache_mb=1e-4)
        assert hist is None
        for _ in range(2):
            b.fit(ListDataSetIterator(data, 32))
        for k, v in b.get_param_table().items():
            np.testing.assert_array_equal(a.get_param_table()[k], v)

    def test_tbptt_config_falls_back_to_fit(self):
        from deeplearning4j_tpu.nn.conf.enums import BackpropType

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.02)
            .updater(Updater.SGD).list()
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_forward_length(4).t_bptt_backward_length(4)
            .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
            .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                       loss_function=LossFunction.MCXENT))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        hist = net.fit_epochs(ListDataSetIterator(_rnn_data(16, t=8), 8), 2)
        assert hist is None
        assert np.isfinite(net.score_value)
        assert net.iteration_count > 0

    def test_cache_plus_fallback_config_raises(self):
        from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
            .optimization_algo(OptimizationAlgorithm.LBFGS).list()
            .layer(0, L.DenseLayer(n_in=6, n_out=8, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=8, n_out=3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(), 32))
        with pytest.raises(ValueError, match="per-step fit loop"):
            net.fit_epochs(cache, 2)


class TestEarlyStoppingFused:
    def _config(self, data, **kw):
        from deeplearning4j_tpu.earlystopping import (
            DataSetLossCalculator, EarlyStoppingConfiguration,
            MaxEpochsTerminationCondition)

        builder = (EarlyStoppingConfiguration.Builder()
                   .epoch_termination_conditions(
                       MaxEpochsTerminationCondition(kw.get("max_epochs", 3)))
                   .score_calculator(
                       DataSetLossCalculator(ListDataSetIterator(data, 32))))
        if kw.get("iter_conditions"):
            builder.iteration_termination_conditions(*kw["iter_conditions"])
        return builder.build()

    def test_fused_trainer_one_dispatch_per_epoch(self):
        from deeplearning4j_tpu.earlystopping import EarlyStoppingTrainer

        data = _ff_data(100, seed=5)
        net = _ff_net()
        trainer = EarlyStoppingTrainer(
            self._config(data), net, ListDataSetIterator(data, 32),
            fuse_epochs=True)
        result = trainer.fit()
        assert result.total_epochs == 3
        assert result.best_model is not None
        assert np.isfinite(result.best_model_score)
        # the cache was built once; each epoch was ONE fused dispatch
        assert net._train_dispatches == 3

    def test_fused_trainer_iteration_condition_sees_every_batch(self):
        from deeplearning4j_tpu.earlystopping import (
            EarlyStoppingResult, EarlyStoppingTrainer,
            MaxScoreIterationTerminationCondition)

        data = _ff_data(100, seed=5)
        trainer = EarlyStoppingTrainer(
            self._config(data, iter_conditions=[
                MaxScoreIterationTerminationCondition(1e-9)]),
            _ff_net(), ListDataSetIterator(data, 32), fuse_epochs=True)
        result = trainer.fit()
        # per-batch losses from the [1, N] history trip the condition
        assert (result.termination_reason
                is EarlyStoppingResult.TerminationReason.ITERATION_TERMINATION)
        assert result.total_epochs == 1


class TestAsyncIteratorLifecycle:
    def _batches(self, n=10):
        return ListDataSetIterator(_ff_data(n * 8, seed=9), batch_size=8)

    def test_reset_midepoch_joins_producer(self):
        it = AsyncDataSetIterator(self._batches(), queue_size=2)
        assert it.has_next()
        it.next()  # mid-epoch
        thread = it._thread
        it.reset()
        assert thread is not None and not thread.is_alive()
        assert it._thread is None
        # and the restarted generation yields the full epoch
        assert len(list(it)) == 10

    def test_repeated_midepoch_resets_do_not_accumulate_threads(self):
        it = AsyncDataSetIterator(self._batches(), queue_size=2)
        baseline = threading.active_count()
        for _ in range(5):
            assert it.has_next()
            it.next()
            it.reset()
        deadline = time.time() + 5
        while threading.active_count() > baseline and time.time() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= baseline

    def test_straggler_generation_cannot_pollute_new_queue(self):
        class Slow(ListDataSetIterator):
            def next(self, num=None):
                time.sleep(0.02)
                return super().next(num)

        ds = _ff_data(40, seed=9)
        it = AsyncDataSetIterator(Slow(ds, batch_size=8), queue_size=2)
        assert it.has_next()
        it.reset()  # old producer may still be mid-next()
        batches = list(it)
        # exactly one epoch: no stale batch from the previous generation
        assert len(batches) == 5
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(b.features) for b in batches]),
            np.asarray(ds.features))

    def test_queue_size_governs_device_buffer_depth(self):
        class Counting(ListDataSetIterator):
            produced = 0

            def next(self, num=None):
                type(self).produced += 1
                return super().next(num)

        Counting.produced = 0
        it = AsyncDataSetIterator(
            Counting(_ff_data(80, seed=9), batch_size=8), queue_size=3)
        assert it.has_next()  # starts producer, peeks one batch
        deadline = time.time() + 5
        # producer runs ahead: queue(3) + peeked(1) + one in-flight put
        while Counting.produced < 4 and time.time() < deadline:
            time.sleep(0.01)
        assert 4 <= Counting.produced <= 5
        time.sleep(0.1)  # no further production while consumer idles
        assert Counting.produced <= 5
        it.reset()
