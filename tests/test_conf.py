"""Config DSL tests: builder, JSON round-trip, shape inference.

Models the reference's nn/conf test suite
(MultiLayerNeuralNetConfigurationTest.java, LayerConfigTest.java — SURVEY §4:
"JSON↔object round-trip for every layer type; validation errors").
"""

import pytest

from deeplearning4j_tpu.nn.conf import (
    ComputationGraphConfiguration,
    InputType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToRnnPreProcessor,
)
from deeplearning4j_tpu.ops.losses import LossFunction


def mlp_conf():
    return (
        NeuralNetConfiguration.Builder()
        .seed(42)
        .learning_rate(0.05)
        .updater(Updater.ADAM)
        .weight_init(WeightInit.XAVIER)
        .list()
        .layer(0, L.DenseLayer(n_in=8, n_out=16, activation="relu"))
        .layer(1, L.OutputLayer(n_in=16, n_out=3,
                                loss_function=LossFunction.MCXENT))
        .build()
    )


class TestBuilder:
    def test_global_defaults_applied(self):
        conf = mlp_conf()
        assert conf.layers[0].updater == Updater.ADAM
        assert conf.layers[0].learning_rate == 0.05
        assert conf.global_conf.seed == 42

    def test_layer_overrides_global(self):
        conf = (
            NeuralNetConfiguration.Builder()
            .learning_rate(0.1)
            .list()
            .layer(0, L.DenseLayer(n_in=2, n_out=2, learning_rate=0.9))
            .layer(1, L.OutputLayer(n_in=2, n_out=2))
            .build()
        )
        assert conf.layers[0].learning_rate == 0.9
        assert conf.layers[1].learning_rate == 0.1

    def test_contiguous_indices_enforced(self):
        b = NeuralNetConfiguration.Builder().list()
        b.layer(0, L.DenseLayer(n_in=2, n_out=2))
        b.layer(2, L.OutputLayer(n_in=2, n_out=2))
        with pytest.raises(ValueError):
            b.build()

    def test_missing_nin_caught(self):
        b = (NeuralNetConfiguration.Builder().list()
             .layer(0, L.DenseLayer(n_out=4)))
        with pytest.raises(ValueError):
            b.build()


ALL_LAYER_CONFS = [
    L.DenseLayer(n_in=4, n_out=5, activation="relu"),
    L.OutputLayer(n_in=5, n_out=3, loss_function=LossFunction.MCXENT),
    L.RnnOutputLayer(n_in=5, n_out=3),
    L.LossLayer(),
    L.EmbeddingLayer(n_in=100, n_out=8),
    L.ActivationLayer(activation="tanh"),
    L.DropoutLayer(dropout=0.5),
    L.ConvolutionLayer(n_in=1, n_out=6, kernel_size=(5, 5), stride=(1, 1)),
    L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)),
    L.BatchNormalization(n_in=7, n_out=7),
    L.LocalResponseNormalization(),
    L.GravesLSTM(n_in=4, n_out=6),
    L.GravesBidirectionalLSTM(n_in=4, n_out=6),
    L.GRU(n_in=4, n_out=6),
    L.LSTM(n_in=4, n_out=6),
    L.AutoEncoder(n_in=10, n_out=4, corruption_level=0.2),
    L.RBM(n_in=10, n_out=4, k=2),
]


class TestSerde:
    @pytest.mark.parametrize("layer", ALL_LAYER_CONFS,
                             ids=lambda l: type(l).__name__)
    def test_layer_roundtrip(self, layer):
        d = layer.to_dict()
        restored = L.LayerConf.from_dict(d)
        assert type(restored) is type(layer)
        assert restored.to_dict() == d

    def test_multilayer_json_roundtrip(self):
        conf = mlp_conf()
        js = conf.to_json()
        restored = MultiLayerConfiguration.from_json(js)
        assert restored == conf
        assert restored.to_json() == js

    def test_preprocessor_roundtrip(self):
        conf = (
            NeuralNetConfiguration.Builder().list()
            .layer(0, L.DenseLayer(n_in=12, n_out=4))
            .layer(1, L.OutputLayer(n_in=4, n_out=2))
            .input_pre_processor(0, CnnToFeedForwardPreProcessor(2, 2, 3))
            .build()
        )
        restored = MultiLayerConfiguration.from_json(conf.to_json())
        assert isinstance(restored.input_preprocessors[0],
                          CnnToFeedForwardPreProcessor)
        assert restored == conf


class TestShapeInference:
    def test_lenet_shapes(self):
        conf = (
            NeuralNetConfiguration.Builder()
            .list()
            .layer(0, L.ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
            .layer(1, L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(2, L.ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
            .layer(3, L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(4, L.DenseLayer(n_out=500, activation="relu"))
            .layer(5, L.OutputLayer(n_out=10))
            .set_input_type(InputType.convolutional(28, 28, 1))
            .build()
        )
        assert conf.layers[0].n_in == 1
        assert conf.layers[2].n_in == 20
        # 28 → conv5 → 24 → pool2 → 12 → conv5 → 8 → pool2 → 4
        assert conf.layers[4].n_in == 4 * 4 * 50
        assert conf.layers[5].n_in == 500
        # CNN → FF preprocessor auto-inserted before the dense layer
        assert 4 in conf.input_preprocessors

    def test_rnn_inference(self):
        conf = (
            NeuralNetConfiguration.Builder().list()
            .layer(0, L.GravesLSTM(n_out=32))
            .layer(1, L.RnnOutputLayer(n_out=5))
            .set_input_type(InputType.recurrent(10))
            .build()
        )
        assert conf.layers[0].n_in == 10
        assert conf.layers[1].n_in == 32

    def test_ff_to_rnn_preprocessor_inserted(self):
        conf = (
            NeuralNetConfiguration.Builder().list()
            .layer(0, L.DenseLayer(n_out=16))
            .layer(1, L.GravesLSTM(n_out=8))
            .layer(2, L.RnnOutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(10))
            .build()
        )
        assert isinstance(conf.input_preprocessors[1], FeedForwardToRnnPreProcessor)
        assert conf.layers[1].n_in == 16


class TestGraphConf:
    def build_graph(self):
        return (
            NeuralNetConfiguration.Builder()
            .learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense1", L.DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("dense2", L.DenseLayer(n_in=4, n_out=8), "in")
            .add_vertex("merge", MergeVertex(), "dense1", "dense2")
            .add_layer("out", L.OutputLayer(n_in=16, n_out=3), "merge")
            .set_outputs("out")
            .build()
        )

    def test_topo_order(self):
        conf = self.build_graph()
        order = conf.topological_order
        assert order.index("in") < order.index("dense1")
        assert order.index("dense1") < order.index("merge")
        assert order.index("merge") < order.index("out")

    def test_json_roundtrip(self):
        conf = self.build_graph()
        restored = ComputationGraphConfiguration.from_json(conf.to_json())
        assert restored == conf

    def test_cycle_detected(self):
        from deeplearning4j_tpu.nn.conf.neural_net import GlobalConf

        with pytest.raises(ValueError):
            ComputationGraphConfiguration(
                GlobalConf(), inputs=["in"], outputs=["a"],
                layers={"a": L.DenseLayer(n_in=2, n_out=2),
                        "b": L.DenseLayer(n_in=2, n_out=2)},
                vertices={},
                vertex_inputs={"a": ["b"], "b": ["a"]},
            )

    def test_unknown_input_detected(self):
        b = (NeuralNetConfiguration.Builder().graph_builder()
             .add_inputs("in")
             .add_layer("out", L.OutputLayer(n_in=2, n_out=2), "missing")
             .set_outputs("out"))
        with pytest.raises(ValueError):
            b.build()

    def test_elementwise_vertex_conf(self):
        conf = (
            NeuralNetConfiguration.Builder().graph_builder()
            .add_inputs("in")
            .add_layer("a", L.DenseLayer(n_in=4, n_out=8), "in")
            .add_layer("b", L.DenseLayer(n_in=4, n_out=8), "in")
            .add_vertex("add", ElementWiseVertex(op="Add"), "a", "b")
            .add_layer("out", L.OutputLayer(n_in=8, n_out=2), "add")
            .set_outputs("out")
            .build()
        )
        restored = ComputationGraphConfiguration.from_json(conf.to_json())
        assert restored.vertices["add"].op == "Add"
