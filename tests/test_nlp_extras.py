"""Viterbi, inverted index, moving windows, stop words, SWN3 (reference:
util/Viterbi.java, text/invertedindex/LuceneInvertedIndex.java,
text/movingwindow/, text/stopwords/StopWords.java, sentiwordnet/SWN3.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.invertedindex import InvertedIndex
from deeplearning4j_tpu.nlp.movingwindow import (
    BEGIN,
    END,
    moving_window_matrix,
    window_indices,
    windows,
)
from deeplearning4j_tpu.nlp.sentiwordnet import SWN3
from deeplearning4j_tpu.nlp.stopwords import (
    get_stop_words,
    is_stop_word,
    remove_stop_words,
)
from deeplearning4j_tpu.nlp.viterbi import Viterbi


class TestViterbi:
    def test_argmax_when_uniform_transitions(self):
        v = Viterbi(3)
        emissions = np.log(np.array([[0.7, 0.2, 0.1],
                                     [0.1, 0.8, 0.1],
                                     [0.2, 0.1, 0.7]], np.float32))
        path, score = v.decode(emissions)
        np.testing.assert_array_equal(path, [0, 1, 2])
        assert np.isfinite(score)

    def test_transitions_override_emissions(self):
        # sticky transitions: staying is much cheaper than switching
        trans = np.log(np.array([[0.95, 0.05], [0.05, 0.95]], np.float32))
        v = Viterbi(2, transitions=trans)
        # emissions weakly prefer flip-flopping 0,1,0,1
        e = np.log(np.array([[0.6, 0.4], [0.45, 0.55],
                             [0.6, 0.4], [0.45, 0.55]], np.float32))
        path, _ = v.decode(e)
        np.testing.assert_array_equal(path, [0, 0, 0, 0])

    def test_exhaustive_agreement(self):
        """DP result equals brute-force max over all 3^4 paths."""
        rng = np.random.default_rng(0)
        S, T = 3, 4
        trans = rng.normal(size=(S, S)).astype(np.float32)
        init = rng.normal(size=(S,)).astype(np.float32)
        e = rng.normal(size=(T, S)).astype(np.float32)
        v = Viterbi(S, transitions=trans, initial=init)
        path, score = v.decode(e)

        import itertools

        def path_score(p):
            s = init[p[0]] + e[0, p[0]]
            for t in range(1, T):
                s += trans[p[t - 1], p[t]] + e[t, p[t]]
            return s

        best = max(itertools.product(range(S), repeat=T), key=path_score)
        assert abs(score - path_score(best)) < 1e-4
        np.testing.assert_array_equal(path, best)

    def test_masked_decode_equals_unpadded(self):
        """decode(length=n) over bucket-padded emissions must equal the
        unpadded decode EXACTLY for every prefix length (the padding is
        inert: identity backpointers, carried delta) — this is what lets
        the POS tagger compile once per bucket instead of per sentence
        length."""
        rng = np.random.default_rng(3)
        S, T_pad = 4, 16
        trans = rng.normal(size=(S, S)).astype(np.float32)
        init = rng.normal(size=(S,)).astype(np.float32)
        v = Viterbi(S, transitions=trans, initial=init)
        for n in (1, 2, 5, 9, 16):
            e = rng.normal(size=(n, S)).astype(np.float32)
            ref_path, ref_score = v.decode(e)
            padded = np.zeros((T_pad, S), np.float32)
            padded[:n] = e
            # garbage in the padding must not leak into the result
            padded[n:] = rng.normal(size=(T_pad - n, S)) * 10
            path, score = v.decode(padded, length=n)
            np.testing.assert_array_equal(path, ref_path)
            assert abs(score - ref_score) < 1e-4
        with pytest.raises(ValueError, match="out of range"):
            v.decode(np.zeros((4, S), np.float32), length=5)

    def test_batch_decode(self):
        v = Viterbi(2)
        e = np.log(np.array([[[0.9, 0.1]] * 3, [[0.1, 0.9]] * 3], np.float32))
        paths, scores = v.decode_batch(e)
        np.testing.assert_array_equal(paths, [[0, 0, 0], [1, 1, 1]])
        assert scores.shape == (2,)

    def test_from_counts(self):
        counts = np.array([[8, 2], [1, 9]], np.float64)
        v = Viterbi.from_counts(counts)
        assert v.transitions.shape == (2, 2)
        assert float(v.transitions[0, 0]) > float(v.transitions[0, 1])

    def test_shape_validation(self):
        with pytest.raises(ValueError):
            Viterbi(3).decode(np.zeros((4, 2), np.float32))


class TestInvertedIndex:
    def _index(self):
        ix = InvertedIndex()
        ix.add_words_to_doc(0, ["the", "cat", "sat"], label="a")
        ix.add_words_to_doc(1, ["the", "dog", "sat", "sat"], label="b")
        ix.add_words_to_doc(2, ["a", "bird"], label="a")
        return ix

    def test_postings_and_counts(self):
        ix = self._index()
        assert ix.documents("sat") == [0, 1]
        assert ix.documents("bird") == [2]
        assert ix.documents("unknown") == []
        assert ix.num_documents() == 3
        assert ix.num_documents("the") == 2
        assert ix.doc_frequency("sat") == 2
        assert ix.label(1) == "b"

    def test_duplicate_doc_rejected(self):
        ix = self._index()
        with pytest.raises(KeyError):
            ix.add_words_to_doc(0, ["x"])

    def test_add_doc_autoid(self):
        ix = self._index()
        new_id = ix.add_doc(["new", "doc"])
        assert new_id == 3
        assert ix.document(3) == ["new", "doc"]

    def test_tfidf_rare_word_scores_higher(self):
        ix = self._index()
        scores = ix.tfidf(0)
        assert scores["cat"] > scores["the"]  # "the" in 2 docs, "cat" in 1

    def test_batch_iter(self):
        ix = self._index()
        batches = list(ix.batch_iter(2))
        assert [len(b) for b in batches] == [2, 1]
        shuffled = list(ix.batch_iter(2, shuffle=True, seed=0))
        assert sum(len(b) for b in shuffled) == 3


class TestMovingWindow:
    def test_windows_padding_and_focus(self):
        ws = windows(["i", "like", "cats"], window_size=3)
        assert len(ws) == 3
        assert ws[0].words == [BEGIN, "i", "like"]
        assert ws[0].focus_word == "i"
        assert ws[2].words == ["like", "cats", END]
        assert ws[2].focus_word == "cats"

    def test_even_window_rejected(self):
        with pytest.raises(ValueError):
            windows(["a"], window_size=4)

    def test_window_indices(self):
        vocab = {"<s>": 0, "i": 1, "like": 2, "cats": 3, "</s>": 4}
        idx = window_indices(["i", "like", "cats"], vocab, window_size=3)
        assert idx.shape == (3, 3)
        np.testing.assert_array_equal(idx[0], [0, 1, 2])
        np.testing.assert_array_equal(idx[2], [2, 3, 4])

    def test_moving_window_matrix(self):
        x = np.arange(12).reshape(4, 3)
        m = moving_window_matrix(x, 2)
        assert m.shape == (3, 2, 3)
        np.testing.assert_array_equal(m[0], x[:2])
        aug = moving_window_matrix(x, 2, add_rotations=True)
        assert aug.shape == (6, 2, 3)
        with pytest.raises(ValueError):
            moving_window_matrix(x, 9)


class TestStopWords:
    def test_basics(self):
        assert is_stop_word("The")
        assert not is_stop_word("neural")
        assert "the" in get_stop_words()
        assert remove_stop_words(["the", "neural", "net", "is", "good"]) == \
            ["neural", "net", "good"]


class TestSWN3:
    def test_word_scores(self):
        swn = SWN3()
        assert swn.extract("good") > 0
        assert swn.extract("awful") < 0
        assert swn.extract("xylophone") == 0.0

    def test_classify_bands(self):
        swn = SWN3()
        assert swn.classify(["excellent", "wonderful"]) == "strong_positive"
        assert swn.classify(["terrible", "horrible"]) == "strong_negative"
        assert swn.classify(["table", "chair"]) == "neutral"
        assert swn.class_for_score(0.5) == "positive"
        assert swn.class_for_score(-0.5) == "negative"

    def test_load_custom_lexicon(self, tmp_path):
        p = tmp_path / "swn.txt"
        p.write_text("# comment\na\t1\t0.9\t0.1\tshiny#1\n"
                     "a\t2\t0.0\t1.0\tgrim#1 grim#2\n")
        swn = SWN3(str(p))
        assert swn.extract("shiny") == pytest.approx(0.8)
        assert swn.extract("grim") == pytest.approx(-1.0)
        assert swn.extract("good") == 0.0  # builtin not loaded
