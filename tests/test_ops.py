"""Tensor-substrate tests: activations, losses (+masking), initializers."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.ops.activations import activation_names, get_activation
from deeplearning4j_tpu.ops.initializers import init_weights
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss


class TestActivations:
    def test_known_values(self):
        x = jnp.asarray([-1.0, 0.0, 1.0])
        np.testing.assert_allclose(get_activation("relu")(x), [0.0, 0.0, 1.0])
        np.testing.assert_allclose(get_activation("identity")(x), x)
        np.testing.assert_allclose(
            get_activation("sigmoid")(jnp.asarray([0.0])), [0.5])
        np.testing.assert_allclose(
            get_activation("tanh")(x), np.tanh(np.asarray(x)), rtol=1e-5)
        np.testing.assert_allclose(
            get_activation("softsign")(x), [-0.5, 0.0, 0.5])
        np.testing.assert_allclose(get_activation("cube")(x), [-1.0, 0.0, 1.0])
        np.testing.assert_allclose(get_activation("hardtanh")(
            jnp.asarray([-2.0, 0.5, 3.0])), [-1.0, 0.5, 1.0])

    def test_softmax_rows_sum_to_one(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 7)))
        s = get_activation("softmax")(x)
        np.testing.assert_allclose(jnp.sum(s, axis=-1), np.ones(4), rtol=1e-6)

    def test_all_registered_names_callable(self):
        x = jnp.asarray([[0.1, 0.2], [0.3, 0.4]])
        for name in activation_names():
            y = get_activation(name)(x)
            assert y.shape == x.shape

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            get_activation("nope")


class TestLosses:
    def test_mse(self):
        out = jnp.asarray([[1.0, 2.0]])
        y = jnp.asarray([[0.0, 0.0]])
        # mean over features then batch: (1 + 4)/2 = 2.5
        np.testing.assert_allclose(compute_loss("MSE", out, y), 2.5)

    def test_mcxent_perfect_prediction_near_zero(self):
        out = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        y = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        assert float(compute_loss(LossFunction.MCXENT, out, y)) < 1e-6

    def test_mcxent_known_value(self):
        out = jnp.asarray([[0.5, 0.5]])
        y = jnp.asarray([[1.0, 0.0]])
        np.testing.assert_allclose(
            compute_loss(LossFunction.MCXENT, out, y), np.log(2.0), rtol=1e-5)

    def test_xent_binary(self):
        out = jnp.asarray([[0.5]])
        y = jnp.asarray([[1.0]])
        np.testing.assert_allclose(
            compute_loss(LossFunction.XENT, out, y), np.log(2.0), rtol=1e-5)

    def test_masking_excludes_entries(self):
        out = jnp.asarray([[1.0, 0.0], [0.5, 0.5]])
        y = jnp.asarray([[1.0, 0.0], [1.0, 0.0]])
        mask = jnp.asarray([1.0, 0.0])
        # only the perfect row counts
        assert float(compute_loss("MCXENT", out, y, mask)) < 1e-6
        mask2 = jnp.asarray([0.0, 1.0])
        np.testing.assert_allclose(
            compute_loss("MCXENT", out, y, mask2), np.log(2.0), rtol=1e-5)

    def test_timeseries_mask(self):
        # [b=1, t=2, f=2]: second step masked out
        out = jnp.asarray([[[0.5, 0.5], [0.9, 0.1]]])
        y = jnp.asarray([[[1.0, 0.0], [0.0, 1.0]]])
        mask = jnp.asarray([[1.0, 0.0]])
        np.testing.assert_allclose(
            compute_loss("MCXENT", out, y, mask), np.log(2.0), rtol=1e-5)

    def test_all_kinds_finite(self):
        rng = np.random.default_rng(1)
        out = jnp.asarray(jax.nn.softmax(jnp.asarray(rng.normal(size=(3, 4)))))
        y = jnp.asarray(np.eye(4)[rng.integers(0, 4, 3)])
        for lf in LossFunction:
            if lf == LossFunction.CUSTOM:
                continue
            v = float(compute_loss(lf, out, y))
            assert np.isfinite(v), lf


class TestInitializers:
    def test_zero(self):
        w = init_weights(jax.random.PRNGKey(0), (4, 5), "ZERO")
        assert float(jnp.abs(w).max()) == 0.0

    def test_xavier_scale(self):
        w = init_weights(jax.random.PRNGKey(0), (2000, 1000), "XAVIER")
        expected_std = np.sqrt(2.0 / 3000)
        assert abs(float(w.std()) - expected_std) < 0.1 * expected_std

    def test_relu_scale(self):
        w = init_weights(jax.random.PRNGKey(0), (2000, 100), "RELU")
        expected_std = np.sqrt(2.0 / 2000)
        assert abs(float(w.std()) - expected_std) < 0.1 * expected_std

    def test_uniform_bounds(self):
        w = init_weights(jax.random.PRNGKey(0), (100, 100), "UNIFORM")
        a = 1.0 / np.sqrt(100)
        assert float(w.min()) >= -a and float(w.max()) <= a

    def test_distribution_normal(self):
        w = init_weights(
            jax.random.PRNGKey(0), (5000,), "DISTRIBUTION",
            distribution={"type": "normal", "mean": 2.0, "std": 0.5})
        assert abs(float(w.mean()) - 2.0) < 0.05
        assert abs(float(w.std()) - 0.5) < 0.05

    def test_deterministic_per_key(self):
        w1 = init_weights(jax.random.PRNGKey(7), (3, 3), "XAVIER")
        w2 = init_weights(jax.random.PRNGKey(7), (3, 3), "XAVIER")
        np.testing.assert_array_equal(w1, w2)


class TestGroupedQueryAttention:
    def test_matches_repeated_dot_product_attention(self):
        """GQA must equal attention with K/V explicitly repeated over
        each query-head group, for every mask combination."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.attention import (
            dot_product_attention, grouped_query_attention)

        rng = np.random.default_rng(0)
        b, tq, tkv, H, hkv, d = 2, 8, 12, 6, 2, 16
        q = jnp.asarray(rng.normal(size=(b, tq, H, d)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(b, tkv, hkv, d)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(b, tkv, hkv, d)), jnp.float32)
        mask = jnp.asarray(rng.integers(0, 2, (b, tkv)), jnp.float32)
        mask = mask.at[:, 0].set(1.0)  # no fully-masked rows
        kr = jnp.repeat(k, H // hkv, axis=2)
        vr = jnp.repeat(v, H // hkv, axis=2)
        for kwargs in ({}, {"causal": True}, {"mask": mask},
                       {"causal": True, "mask": mask}):
            ref = dot_product_attention(q, kr, vr, **kwargs)
            got = grouped_query_attention(q, k, v, **kwargs)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-6, atol=2e-6)

    def test_head_count_guard_and_delegation(self):
        import pytest as _pytest
        import jax.numpy as jnp
        from deeplearning4j_tpu.ops.attention import (
            grouped_query_attention)

        q = jnp.ones((1, 4, 6, 8))
        kv = jnp.ones((1, 4, 4, 8))
        with _pytest.raises(ValueError, match="not a multiple"):
            grouped_query_attention(q, kv, kv)
