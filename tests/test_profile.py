"""Compiled-program performance observatory tests (monitor/profile +
monitor/memory + scripts/bench_report.py).

The contracts that matter:

1. ``DL4J_PROFILE`` off (the default) leaves the fused path untouched —
   trained params are BITWISE identical to the profile-on run for
   FF/RNN/graph and the SPMD wrapper (profiling changes when the numbers
   are read, never what runs).
2. With it on, every cached ``_epoch_steps`` key carries a
   ProgramProfile with nonzero cost-analysis FLOPs and a
   memory-analysis peak, and cost-analysis FLOPs agree with the
   analytic formula on a known GEMM.
3. The epoch-cache per-shard HBM budget model matches the bytes the
   devices actually hold (``validate_cache_budget``), and watermarks
   sample at chunk boundaries only.
4. ``bench_report.py`` flags wedge/error rounds, never scores them, and
   exits nonzero on an injected regression.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.analysis.engine import LintConfig, run_lint
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.monitor import (
    MetricsRegistry,
    SpanTracer,
    metrics,
    set_tracer,
    tracer,
)
from deeplearning4j_tpu.monitor.memory import (
    cache_resident_bytes,
    live_array_bytes,
    sample_hbm_watermark,
    validate_cache_budget,
)
from deeplearning4j_tpu.monitor.profile import (
    ProfiledProgram,
    ProfileStore,
    capture_program_profile,
    classify_boundedness,
    flops_divergence_pct,
    profile_enabled,
    profiles,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.perf.epoch_cache import DeviceDataSetCache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench_report():
    spec = importlib.util.spec_from_file_location(
        "bench_report", os.path.join(REPO, "scripts", "bench_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_report = _load_bench_report()


@pytest.fixture(autouse=True)
def _fresh_observability(monkeypatch):
    """Every test sees an empty registry/tracer/profile store and the
    DL4J_PROFILE default (off); nothing leaks out."""
    monkeypatch.delenv("DL4J_PROFILE", raising=False)
    metrics().reset()
    profiles().reset()
    set_tracer(SpanTracer())
    yield
    metrics().reset()
    profiles().reset()
    set_tracer(None)


# ---------------------------------------------------------------------------
# model/data helpers (the test_telemetry shapes)
# ---------------------------------------------------------------------------


def _ff_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
        .updater(Updater.SGD).list()
        .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build())


def _ff_data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _rnn_data(n=24, t=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    return DataSet(x, y)


def _bitwise_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all((np.asarray(x) == np.asarray(y)).all()
               for x, y in zip(la, lb))


MAKERS = {
    "ff": (_ff_net, lambda: _ff_data(48)),
    "rnn": (_rnn_net, lambda: _rnn_data(24)),
    "graph": (_ff_graph, lambda: _ff_data(48)),
}


# ---------------------------------------------------------------------------
# capture_program_profile
# ---------------------------------------------------------------------------


class TestCaptureProgramProfile:
    def test_gemm_flops_agree_with_analytic(self):
        """cost-analysis FLOPs vs the textbook 2*n^3 on a known GEMM —
        the cross-check that anchors every cost-derived MFU number."""
        n = 256
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((n, n), jnp.float32)
        prof, compiled = capture_program_profile(
            f, (a, a), name="gemm", key=("test",))
        analytic = 2.0 * n ** 3
        assert prof.flops is not None and prof.flops > 0
        div = flops_divergence_pct(analytic, prof.flops)
        assert abs(div) < 5.0, f"GEMM flops diverged {div}%"
        # the returned executable computes the same thing
        out = compiled(a, a)
        assert np.allclose(np.asarray(out), np.asarray(f(a, a)))

    def test_memory_analysis_peak_nonzero(self):
        f = jax.jit(lambda a: a * 2.0)
        a = jnp.ones((64, 64), jnp.float32)
        prof, _ = capture_program_profile(f, (a,), name="mul",
                                          key=("test",))
        assert prof.argument_bytes and prof.argument_bytes >= a.nbytes
        assert prof.output_bytes and prof.output_bytes >= a.nbytes
        assert prof.peak_bytes and prof.peak_bytes > 0
        assert prof.compile_s is not None and prof.compile_s > 0
        assert prof.lower_s is not None

    def test_registry_and_span_mirror(self):
        f = jax.jit(lambda a: a + 1)
        capture_program_profile(f, (jnp.ones(8),), name="inc",
                                key=(1, 2))
        snap = metrics().snapshot()
        assert "program_flops" in snap
        assert "program_peak_hbm_bytes" in snap
        assert "program_compile_seconds" in snap
        labels = snap["program_flops"]["values"][0]["labels"]
        assert labels["program"] == "inc"
        assert metrics().counter("program_profiles_total").value(
            program="inc", outcome="ok") == 1
        names = [sp.name for sp in tracer().spans()]
        assert "profile.capture" in names

    def test_store_snapshot_is_json_ready(self):
        store = ProfileStore()
        f = jax.jit(lambda a: a + 1)
        capture_program_profile(f, (jnp.ones(8),), name="inc",
                                key=("k",), store=store)
        snap = store.snapshot()
        assert len(snap) == 1
        json.dumps(snap)
        assert snap[0]["name"] == "inc"
        assert snap[0]["flops"] is not None


# ---------------------------------------------------------------------------
# profile-on vs profile-off parity + per-key profiles
# ---------------------------------------------------------------------------


class TestProfiledFusedPrograms:
    @pytest.mark.parametrize("kind", ["ff", "rnn", "graph"])
    def test_profile_on_off_params_bitwise(self, kind, monkeypatch):
        make_net, make_data = MAKERS[kind]
        ds = make_data()

        monkeypatch.setenv("DL4J_PROFILE", "0")
        off = make_net()
        off.fit_epochs(ListDataSetIterator(ds, 12), 3)

        monkeypatch.setenv("DL4J_PROFILE", "1")
        on = make_net()
        on.fit_epochs(ListDataSetIterator(ds, 12), 3)

        assert _bitwise_equal(off.params, on.params)
        assert _bitwise_equal(off.updater_state, on.updater_state)

    def test_every_cached_key_has_a_profile(self, monkeypatch):
        monkeypatch.setenv("DL4J_PROFILE", "1")
        net = _ff_net()
        ds = _ff_data(48)
        net.fit_epochs(ListDataSetIterator(ds, 12), 2)
        net.fit_epochs(ListDataSetIterator(ds, 12), 2, telemetry=1)
        assert len(net._epoch_steps) == 2
        for key, program in net._epoch_steps.items():
            assert isinstance(program, ProfiledProgram)
            assert program.profiles, f"no profile captured for {key}"
            prof = program.profiles[0]
            assert prof.key == key
            assert prof.flops and prof.flops > 0
            assert prof.peak_bytes and prof.peak_bytes > 0
        # and they all landed in the process-global store
        assert len(profiles().find(name="MultiLayerNetwork")) == 2

    def test_profile_off_keeps_plain_path(self):
        net = _ff_net()
        net.fit_epochs(ListDataSetIterator(_ff_data(48), 12), 2)
        program = next(iter(net._epoch_steps.values()))
        assert isinstance(program, ProfiledProgram)
        assert program.profiles == []
        assert program._compiled == {}
        assert profiles().all() == []

    def test_wrapper_spmd_profile_parity(self, monkeypatch):
        from deeplearning4j_tpu.parallel import ParallelWrapper, build_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs the forced multi-device host platform")
        ds = _ff_data(64)

        def run():
            net = _ff_net()
            wrapper = ParallelWrapper(net, mesh=build_mesh())
            cache = wrapper.build_epoch_cache(ListDataSetIterator(ds, 16))
            assert cache is not None
            wrapper.fit_epochs(cache, 2)
            return net, wrapper

        monkeypatch.setenv("DL4J_PROFILE", "0")
        off, _ = run()
        monkeypatch.setenv("DL4J_PROFILE", "1")
        on, wrapper = run()
        assert _bitwise_equal(off.params, on.params)
        program = next(iter(wrapper._epoch_steps.values()))
        assert program.profiles
        assert program.profiles[0].flops > 0
        assert profiles().find(name="ParallelWrapper")

    def test_one_capture_per_signature(self, monkeypatch):
        """A second same-shaped run reuses the compiled executable; a
        new chunk length (new epoch_keys shape) captures exactly one
        more profile."""
        monkeypatch.setenv("DL4J_PROFILE", "1")
        net = _ff_net()
        ds = _ff_data(48)
        net.fit_epochs(ListDataSetIterator(ds, 12), 2)
        program = next(iter(net._epoch_steps.values()))
        assert len(program.profiles) == 1
        net.fit_epochs(ListDataSetIterator(ds, 12), 2)
        assert len(program.profiles) == 1  # same signature: no recapture
        net.fit_epochs(ListDataSetIterator(ds, 12), 3)
        assert len(program.profiles) == 2  # new chunk length

    def test_contracts_accept_profiled_programs(self):
        """The PR-7 program-contract checker keeps working against
        ProfiledProgram cache entries (lower/trace delegate)."""
        from deeplearning4j_tpu.analysis.contracts import (
            check_network_contracts)

        net = _ff_net()
        cache = net.build_epoch_cache(
            ListDataSetIterator(_ff_data(48), 12))
        net.fit_epochs(cache, 2)
        results = check_network_contracts(net, cache)
        assert all(v == [] for v in results.values())


# ---------------------------------------------------------------------------
# HBM watermarks + the budget-model runtime check
# ---------------------------------------------------------------------------


class TestHbmWatermarks:
    def test_sample_shape_and_gauges(self):
        x = jnp.ones((128, 128))  # keep one known live array
        sample = sample_hbm_watermark(tag="test")
        assert sample["tag"] == "test"
        assert sample["devices"]
        for entry in sample["devices"]:
            assert entry["source"] in ("memory_stats", "live_arrays")
            assert entry["bytes_in_use"] >= 0
        assert sample["max_bytes_in_use"] >= x.nbytes // len(
            jax.local_devices())
        snap = metrics().snapshot()
        assert "hbm_bytes_in_use" in snap
        assert any(sp.name == "hbm.watermark" for sp in tracer().spans())

    def test_live_array_accounting_sees_new_allocations(self):
        before = sum(live_array_bytes().values())
        big = jnp.ones((256, 1024), jnp.float32)
        after = sum(live_array_bytes().values())
        assert after - before >= big.nbytes

    def test_budget_model_matches_measured_cache_bytes(self):
        """The per-shard HBM budget model vs runtime allocation: the
        analytic resident bytes the build priced must match the bytes
        the device actually holds for the stacks."""
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(96), 24))
        assert cache is not None
        check = validate_cache_budget(cache)
        assert check["within_tolerance"], check
        assert check["ratio"] == pytest.approx(1.0, abs=0.25)
        measured = cache_resident_bytes(cache)
        assert max(measured.values()) == check[
            "measured_per_device_bytes"]

    def test_watermarks_sampled_per_chunk_only_when_profiling(
            self, monkeypatch):
        ds = _ff_data(48)
        net = _ff_net()
        net.fit_epochs(ListDataSetIterator(ds, 12), 3, chunk_epochs=1)
        assert net._hbm_watermarks is None  # default off: never sampled

        monkeypatch.setenv("DL4J_PROFILE", "1")
        net2 = _ff_net()
        net2.fit_epochs(ListDataSetIterator(ds, 12), 3, chunk_epochs=1)
        assert len(net2._hbm_watermarks) == 3  # one per chunk boundary
        assert all(w["tag"] == "epoch.chunk"
                   for w in net2._hbm_watermarks)


# ---------------------------------------------------------------------------
# the cost model's step-time decomposition
# ---------------------------------------------------------------------------


class TestBoundedness:
    def test_compute_bound(self):
        out = classify_boundedness(
            flops=1e12, bytes_accessed=1e9, measured_s=0.02,
            peak_flops_per_s=1e14, peak_bytes_per_s=1e12)
        assert out["bound"] == "compute"
        assert out["optimal_s"] == pytest.approx(0.01)
        assert out["dispatch_wait_s"] == pytest.approx(0.01)
        assert out["dispatch_wait_pct"] == pytest.approx(50.0)
        assert out["arithmetic_intensity"] == pytest.approx(1000.0)

    def test_memory_bound(self):
        out = classify_boundedness(
            flops=1e9, bytes_accessed=1e10, measured_s=0.05,
            peak_flops_per_s=1e14, peak_bytes_per_s=1e11)
        assert out["bound"] == "memory"
        assert out["optimal_s"] == pytest.approx(0.1)
        assert out["dispatch_wait_s"] == 0.0  # measured below optimum

    def test_missing_inputs_degrade_to_none(self):
        out = classify_boundedness(None, None, None, 1e12, 1e11)
        assert out["bound"] is None
        assert out["optimal_s"] is None
        assert out["dispatch_wait_s"] is None

    def test_flops_divergence(self):
        assert flops_divergence_pct(100.0, 112.0) == pytest.approx(12.0)
        assert flops_divergence_pct(100.0, 95.0) == pytest.approx(-5.0)
        assert flops_divergence_pct(0.0, 95.0) is None
        assert flops_divergence_pct(100.0, None) is None


# ---------------------------------------------------------------------------
# profile-readback lint: chunk-boundary-only by contract
# ---------------------------------------------------------------------------


class TestProfileReadbackLint:
    def _lint(self, tmp_path, source):
        import textwrap

        path = tmp_path / "snippet.py"
        path.write_text(textwrap.dedent(source))
        config = LintConfig(root=str(tmp_path),
                            registered_markers={"chaos", "slow"})
        return run_lint(paths=[str(path)],
                        select=["host-sync-in-hot-path"], config=config)

    def test_profile_readback_in_hot_path_is_flagged(self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.monitor.memory import sample_hbm_watermark

            def _epoch_run_fn(self, xs):
                sample_hbm_watermark(tag="inside the program")
                return xs
            """)
        assert len(found) == 1
        assert "profile-readback" in found[0].message
        assert "chunk boundaries" in found[0].message

    def test_capture_in_traced_function_is_flagged(self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.analysis.annotations import traced
            from deeplearning4j_tpu.monitor.profile import capture_program_profile

            @traced
            def step(fn, args):
                return capture_program_profile(fn, args, name="x")
            """)
        assert len(found) == 1
        assert "profile-readback" in found[0].message

    def test_chunk_boundary_call_is_clean(self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.monitor.memory import sample_hbm_watermark

            def drive_chunks(net):
                # host-side, between dispatches: the permitted site
                return sample_hbm_watermark(tag="epoch.chunk")
            """)
        assert found == []

    def test_shipped_tree_is_lint_clean(self):
        """The new monitor/profile + monitor/memory path (and the chunk
        driver calling into it) introduces no findings."""
        config = LintConfig(root=REPO, registered_markers={"chaos",
                                                           "slow"})
        found = run_lint(
            paths=[os.path.join(REPO, "deeplearning4j_tpu", "monitor",
                                "profile.py"),
                   os.path.join(REPO, "deeplearning4j_tpu", "monitor",
                                "memory.py"),
                   os.path.join(REPO, "deeplearning4j_tpu", "perf",
                                "epoch_cache.py")],
            select=None, config=config)
        assert found == [], [f"{f.rule}: {f.message}" for f in found]


# ---------------------------------------------------------------------------
# bench error-path flush: profiles survive a wedge
# ---------------------------------------------------------------------------


class TestBenchProfileFlush:
    def _load_bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench_under_test", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_error_path_flushes_collected_profiles(self):
        """The PR-6 partial-flush hardening extends to profile data: an
        error-path artifact still carries every ProgramProfile captured
        before the wedge, beside the telemetry block."""
        bench = self._load_bench()
        f = jax.jit(lambda a: a * 3.0)
        capture_program_profile(f, (jnp.ones(16),), name="pre_wedge")
        extras = {"error": "backend unavailable: wedged device grant"}
        bench._refresh_telemetry(extras)
        assert extras["profile"]["programs"], "profiles lost on error path"
        assert extras["profile"]["programs"][0]["name"] == "pre_wedge"
        assert "spans" in extras["telemetry"]
        json.dumps(extras)  # artifact stays JSON-serializable

    def test_flops_entry_and_divergence_flag(self):
        bench = self._load_bench()
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((64, 64), jnp.float32)
        prof, _ = capture_program_profile(f, (a, a), name="gemm64")
        # per=1: whole-program counts; analytic = the textbook 2n^3
        entry = bench._flops_entry(2.0 * 64 ** 3, "2n^3", prof, 1)
        assert entry["cost_analysis_flops"] is not None
        assert abs(entry["flops_divergence_pct"]) < 10.0
        assert entry["flops_divergence_flag"] is False
        # an off-by-2x analytic formula trips the flag
        entry2 = bench._flops_entry(4.0 * 64 ** 3, "4n^3", prof, 1)
        assert entry2["flops_divergence_flag"] is True


# ---------------------------------------------------------------------------
# bench_report.py: trajectory table + regression gate
# ---------------------------------------------------------------------------


def _write_round(tmp_path, n, value, *, metric="m_samples_per_sec",
                 rc=0, parsed=True, error=None, extras=None):
    payload = {"n": n, "rc": rc, "tail": ""}
    if parsed:
        ex = dict(extras or {})
        if error:
            ex["error"] = error
        payload["parsed"] = {"metric": metric, "value": value,
                             "unit": "x", "extras": ex}
    else:
        payload["parsed"] = None
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps(payload))
    return str(path)


class TestBenchReport:
    def test_improvement_exits_zero(self, tmp_path, capsys):
        files = [_write_round(tmp_path, 1, 100.0),
                 _write_round(tmp_path, 2, 130.0)]
        rc = bench_report.main(["--check"] + files)
        assert rc == 0
        out = capsys.readouterr().out
        assert "no regressions" in out

    def test_injected_regression_exits_nonzero(self, tmp_path, capsys):
        files = [_write_round(tmp_path, 1, 100.0),
                 _write_round(tmp_path, 2, 60.0)]
        rc = bench_report.main(["--check"] + files)
        assert rc == 1
        out = capsys.readouterr().out
        assert "REGRESSIONS" in out
        assert "40.0% below" in out

    def test_threshold_is_configurable(self, tmp_path):
        files = [_write_round(tmp_path, 1, 100.0),
                 _write_round(tmp_path, 2, 85.0)]
        assert bench_report.main(["--check"] + files) == 0  # 15% < 20%
        assert bench_report.main(["--check", "--threshold-pct", "10"]
                                 + files) == 1

    def test_wedge_round_is_flagged_and_skipped(self, tmp_path, capsys):
        """A wedge between two honest rounds is called out but neither
        scored as a regression nor used as a baseline."""
        files = [
            _write_round(tmp_path, 1, 100.0),
            _write_round(tmp_path, 2, None,
                         error="backend unavailable: backend init did "
                               "not complete in 90s (wedged device "
                               "grant?)"),
            _write_round(tmp_path, 3, 98.0),
        ]
        rc = bench_report.main(["--check"] + files)
        assert rc == 0  # 2% dip, wedge round contributes nothing
        out = capsys.readouterr().out
        assert "WEDGE" in out
        assert "excluded from regression scoring" in out

    def test_regression_detected_across_wedge_gap(self, tmp_path):
        """The baseline survives the wedge: r03 regressing against r01
        is caught even though r02 recorded only an error line."""
        files = [
            _write_round(tmp_path, 1, 100.0),
            _write_round(tmp_path, 2, None,
                         error="backend unavailable: wedged"),
            _write_round(tmp_path, 3, 50.0),
        ]
        assert bench_report.main(["--check"] + files) == 1

    def test_error_round_without_result_line(self, tmp_path, capsys):
        files = [_write_round(tmp_path, 1, 100.0),
                 _write_round(tmp_path, 2, None, rc=124, parsed=False)]
        assert bench_report.main(["--check"] + files) == 0
        assert "ERROR" in capsys.readouterr().out

    def test_headline_metric_change_is_not_a_trajectory(self, tmp_path):
        """r01's lenet headline vs r03's transformer headline are
        different experiments — never compared."""
        files = [
            _write_round(tmp_path, 1, 2_000_000.0, metric="lenet_sps"),
            _write_round(tmp_path, 2, 74_000.0, metric="tf_tokens"),
        ]
        assert bench_report.main(["--check"] + files) == 0

    def test_section_metrics_are_tracked(self, tmp_path):
        """A regression hiding in a section (headline steady) is still
        caught — the satellite metrics feed the gate too. The MFU series
        engages only for cost-analysis-sourced rounds (PR 14)."""
        cost = {"flops_source": {"cost_analysis_flops": 1.0e9}}
        files = [
            _write_round(tmp_path, 1, 100.0,
                         extras={"transformer_lm": {"mfu_pct": 8.0,
                                                    **cost}}),
            _write_round(tmp_path, 2, 101.0,
                         extras={"transformer_lm": {"mfu_pct": 2.0,
                                                    **cost}}),
        ]
        assert bench_report.main(["--check"] + files) == 1

    def test_analytic_mfu_rounds_never_enter_the_series(self, tmp_path,
                                                        capsys):
        """flops_source != cost_analysis ⇒ the round's MFU is not a
        trajectory point (an analytic number must never baseline or
        regress the compiled-FLOPs series) and the table flags it."""
        files = [
            _write_round(tmp_path, 1, 100.0,
                         extras={"transformer_lm": {
                             "mfu_pct": 8.0,
                             "flops_source": "analytic 6*N/token"}}),
            _write_round(tmp_path, 2, 101.0,
                         extras={"transformer_lm": {
                             "mfu_pct": 2.0,
                             "flops_source": {
                                 "cost_analysis_flops": None}}}),
        ]
        assert bench_report.main(["--check"] + files) == 0
        assert "[flops_source!=cost_analysis]" in capsys.readouterr().out

    def test_bf16_speedup_is_tracked(self, tmp_path):
        files = [
            _write_round(tmp_path, 1, 100.0,
                         extras={"transformer_lm": {
                             "train_step_bf16_speedup": 1.8}}),
            _write_round(tmp_path, 2, 101.0,
                         extras={"transformer_lm": {
                             "train_step_bf16_speedup": 1.0}}),
        ]
        assert bench_report.main(["--check"] + files) == 1

    def test_committed_trajectory(self, capsys):
        """The real BENCH_r01-r05 artifacts: rounds 4-5 flag as wedge
        rounds, round 2 as an error round, and the gate passes (the two
        honest rounds have disjoint metrics)."""
        files = [os.path.join(REPO, f"BENCH_r0{i}.json")
                 for i in range(1, 6)]
        rc = bench_report.main(["--check"] + files)
        out = capsys.readouterr().out
        assert rc == 0
        assert out.count("WEDGE") >= 2
        assert "r04" in out and "r05" in out

    def test_load_error_exit_code(self, tmp_path, capsys):
        missing = str(tmp_path / "BENCH_r99.json")
        assert bench_report.main([missing]) == 2
