"""Mesh-parallel word2vec + distributed evaluation (reference:
dl4j-spark-nlp word2vec; dl4j-spark EvaluateFlatMapFunction +
Evaluation.merge). Runs on the 8-device virtual CPU mesh (conftest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import DistributedWord2Vec, SequenceVectors
from deeplearning4j_tpu.parallel import MeshSpec, build_mesh


def _corpus(rng, n=150):
    groups = [["a", "b", "c"], ["x", "y", "z"]]
    return [[groups[g][i] for i in rng.integers(0, 3, 10)]
            for g in (rng.integers(0, 2, n))]


class TestDistributedWord2Vec:
    def _fit(self, mesh, seqs):
        class DW2V(DistributedWord2Vec, SequenceVectors):
            pass

        vec = DW2V(seqs, mesh=mesh, layer_size=16, window_size=3,
                   negative=5, epochs=6, min_word_frequency=1, seed=1)
        return vec.fit()

    def test_cluster_structure_on_mesh(self, rng):
        mesh = build_mesh(MeshSpec(data=8))
        vec = self._fit(mesh, _corpus(rng))
        assert vec.data_parallelism == 8
        for other in ("x", "y", "z"):
            assert vec.similarity("a", "b") > vec.similarity("a", other)

    def test_matches_single_device_quality(self, rng):
        """The averaged-update semantics must learn the same structure a
        single device learns (not bit-identical — averaging ≠ sequential)."""
        import jax

        seqs = _corpus(rng)
        mesh = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        dist = self._fit(mesh, seqs)
        single = (SequenceVectors.Builder().iterate(seqs).layer_size(16)
                  .window_size(3).negative_sample(5).epochs(6).seed(1)
                  .build()).fit()
        for v in (dist, single):
            assert v.similarity("a", "b") > v.similarity("a", "x")

    def test_pad_batch_not_divisible(self, rng):
        """Odd pair counts must pad, not crash, on a mesh the batch does
        not divide."""
        mesh = build_mesh(MeshSpec(data=8))
        seqs = [["a", "b", "c", "a", "b"]] * 7  # small, odd pair totals
        vec = self._fit(mesh, seqs)
        assert np.isfinite(np.asarray(vec.syn0)).all()


class TestFusedSharded:
    """ISSUE 18: the fused whole-epoch skip-gram program on a mesh — DP
    (batch split inside shard_map) and row-sharded tables (model axis,
    GSPMD) must both stay within 1e-6 of the single-device program."""

    def _sentences(self, rng, n_words=40, n_sent=100):
        words = [f"w{i}" for i in range(n_words)]
        return [" ".join(words[i] for i in rng.integers(0, n_words,
                                                        rng.integers(3, 12)))
                for _ in range(n_sent)]

    def _make(self, sents, mesh=None, **kw):
        from deeplearning4j_tpu.nlp import Word2Vec
        from deeplearning4j_tpu.nlp.sentence_iterator import (
            CollectionSentenceIterator,
        )

        kw.setdefault("min_word_frequency", 1)
        kw.setdefault("layer_size", 16)
        kw.setdefault("window_size", 2)
        kw.setdefault("negative", 3)
        kw.setdefault("seed", 0)
        kw.setdefault("epochs", 2)
        cls = Word2Vec if mesh is None else DistributedWord2Vec
        if mesh is not None:
            kw["mesh"] = mesh
        vec = cls(sentence_iterator=CollectionSentenceIterator(sents),
                  **kw)
        vec.build_vocab()
        vec.reset_weights()
        return vec

    def _single_reference(self, sents, batch):
        from deeplearning4j_tpu.nlp.epoch_kernels import (
            SkipGramCorpusCache,
        )

        sv = self._make(sents)
        cache = SkipGramCorpusCache.build(sv, batch=batch)
        hist = sv.fit_epochs(2, cache=cache)
        return sv, hist

    def test_dp_matches_single_device(self, rng):
        import jax

        sents = self._sentences(rng)
        mesh = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        dw = self._make(sents, mesh=mesh)
        hist = dw.fit_epochs(2)
        assert dw._train_dispatches == 1
        sv, ref_hist = self._single_reference(sents,
                                              dw._corpus_cache.batch)
        np.testing.assert_allclose(np.asarray(hist), np.asarray(ref_hist),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw.syn0),
                                   np.asarray(sv.syn0), atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw.syn1neg),
                                   np.asarray(sv.syn1neg), atol=1e-6)

    def test_row_sharded_matches_single_device(self, rng):
        """Tables P('model', None) from the registry, SAME program under
        GSPMD: physically sharded rows, numerics within 1e-6."""
        import jax

        sents = self._sentences(rng)  # 40 words tile the 2-way model axis
        mesh = build_mesh(MeshSpec(data=1, model=2),
                          devices=jax.devices()[:2])
        dw = self._make(sents, mesh=mesh)
        assert dw._fused_mode(mesh) == "rows"
        hist = dw.fit_epochs(2)
        assert dw._train_dispatches == 1
        reg = dw._sharding_registry
        assert reg is not None and "model" in reg.declared_axes
        shards = dw.syn0.addressable_shards
        assert len(shards) == 2
        assert shards[0].data.shape[0] == dw.vocab.num_words() // 2
        sv, ref_hist = self._single_reference(sents,
                                              dw._corpus_cache.batch)
        np.testing.assert_allclose(np.asarray(hist), np.asarray(ref_hist),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw.syn0),
                                   np.asarray(sv.syn0), atol=1e-6)

    def test_sharded_program_contracts(self, rng):
        """PR-7 checks on the DP program: collectives ONLY over the
        registry-declared axes, donation on both tables."""
        import jax

        from deeplearning4j_tpu.analysis.contracts import (
            check_embedding_contracts,
        )

        mesh = build_mesh(MeshSpec(data=2), devices=jax.devices()[:2])
        dw = self._make(self._sentences(rng), mesh=mesh)
        dw.fit_epochs(2)
        results = check_embedding_contracts(dw, dw._corpus_cache,
                                            epochs=2)
        assert all(not v for v in results.values())

    def test_heartbeat_posts_words_per_sec(self, rng):
        """Workers post words/sec + loss payloads the fleet master tick
        aggregates (step_s / last_loss are the keys it reads)."""
        import jax

        from deeplearning4j_tpu.parallel.statetracker import (
            InMemoryStateTracker,
        )

        mesh = build_mesh(MeshSpec(data=2), devices=jax.devices()[:2])
        dw = self._make(self._sentences(rng), mesh=mesh, epochs=4)
        tracker = InMemoryStateTracker()
        monitor = dw.attach_heartbeat(tracker, "w2v-worker-0",
                                      interval_s=0.05)
        with monitor:
            dw.fit_epochs(4, chunk_epochs=1)
            # stats are refreshed per chunk; force one beat with them
            monitor._post()
        metrics = tracker.heartbeat_metrics("w2v-worker-0")
        assert metrics is not None
        assert metrics["step_s"] > 0
        assert metrics["words_per_sec"] > 0
        assert np.isfinite(metrics["last_loss"])
        assert metrics["epochs_done"] == 4


class TestDistributedEvaluate:
    def test_wrapper_evaluate_merges(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.nn.conf import (NeuralNetConfiguration,
                                                Updater)
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel import ParallelWrapper

        conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.2)
                .updater(Updater.ADAM).list()
                .layer(0, L.DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(1, L.OutputLayer(n_in=16, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        n = 128
        x = np.concatenate([rng.normal(-2, .5, (n // 2, 4)),
                            rng.normal(2, .5, (n // 2, 4))]).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[
            np.r_[np.zeros(n // 2, int), np.ones(n // 2, int)]]
        ds = DataSet(x, y)
        ds.shuffle(seed=0)
        wrapper = ParallelWrapper(net, mesh=build_mesh(MeshSpec(data=8)))
        for _ in range(30):
            wrapper.fit(ds)
        # multi-batch iterator: per-batch evals merge into one
        it = ListDataSetIterator(ds, 32)
        ev = wrapper.evaluate(it)
        assert ev.accuracy() > 0.95
        total = sum(sum(row.values()) for row in ev.confusion.matrix.values())
        assert total == n
        # an odd-sized batch falls back to unsharded forward
        ev2 = wrapper.evaluate(DataSet(x[:17], y[:17]))
        total2 = sum(sum(row.values())
                     for row in ev2.confusion.matrix.values())
        assert total2 == 17
