"""Mesh-parallel word2vec + distributed evaluation (reference:
dl4j-spark-nlp word2vec; dl4j-spark EvaluateFlatMapFunction +
Evaluation.merge). Runs on the 8-device virtual CPU mesh (conftest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import DistributedWord2Vec, SequenceVectors
from deeplearning4j_tpu.parallel import MeshSpec, build_mesh


def _corpus(rng, n=150):
    groups = [["a", "b", "c"], ["x", "y", "z"]]
    return [[groups[g][i] for i in rng.integers(0, 3, 10)]
            for g in (rng.integers(0, 2, n))]


class TestDistributedWord2Vec:
    def _fit(self, mesh, seqs):
        class DW2V(DistributedWord2Vec, SequenceVectors):
            pass

        vec = DW2V(seqs, mesh=mesh, layer_size=16, window_size=3,
                   negative=5, epochs=6, min_word_frequency=1, seed=1)
        return vec.fit()

    def test_cluster_structure_on_mesh(self, rng):
        mesh = build_mesh(MeshSpec(data=8))
        vec = self._fit(mesh, _corpus(rng))
        assert vec.data_parallelism == 8
        for other in ("x", "y", "z"):
            assert vec.similarity("a", "b") > vec.similarity("a", other)

    def test_matches_single_device_quality(self, rng):
        """The averaged-update semantics must learn the same structure a
        single device learns (not bit-identical — averaging ≠ sequential)."""
        import jax

        seqs = _corpus(rng)
        mesh = build_mesh(MeshSpec(data=4), devices=jax.devices()[:4])
        dist = self._fit(mesh, seqs)
        single = (SequenceVectors.Builder().iterate(seqs).layer_size(16)
                  .window_size(3).negative_sample(5).epochs(6).seed(1)
                  .build()).fit()
        for v in (dist, single):
            assert v.similarity("a", "b") > v.similarity("a", "x")

    def test_pad_batch_not_divisible(self, rng):
        """Odd pair counts must pad, not crash, on a mesh the batch does
        not divide."""
        mesh = build_mesh(MeshSpec(data=8))
        seqs = [["a", "b", "c", "a", "b"]] * 7  # small, odd pair totals
        vec = self._fit(mesh, seqs)
        assert np.isfinite(np.asarray(vec.syn0)).all()


class TestDistributedEvaluate:
    def test_wrapper_evaluate_merges(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.nn.conf import (NeuralNetConfiguration,
                                                Updater)
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.parallel import ParallelWrapper

        conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.2)
                .updater(Updater.ADAM).list()
                .layer(0, L.DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(1, L.OutputLayer(n_in=16, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        n = 128
        x = np.concatenate([rng.normal(-2, .5, (n // 2, 4)),
                            rng.normal(2, .5, (n // 2, 4))]).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[
            np.r_[np.zeros(n // 2, int), np.ones(n // 2, int)]]
        ds = DataSet(x, y)
        ds.shuffle(seed=0)
        wrapper = ParallelWrapper(net, mesh=build_mesh(MeshSpec(data=8)))
        for _ in range(30):
            wrapper.fit(ds)
        # multi-batch iterator: per-batch evals merge into one
        it = ListDataSetIterator(ds, 32)
        ev = wrapper.evaluate(it)
        assert ev.accuracy() > 0.95
        total = sum(sum(row.values()) for row in ev.confusion.matrix.values())
        assert total == n
        # an odd-sized batch falls back to unsharded forward
        ev2 = wrapper.evaluate(DataSet(x[:17], y[:17]))
        total2 = sum(sum(row.values())
                     for row in ev2.confusion.matrix.values())
        assert total2 == 17
