"""MultiLayerNetwork integration tests (MultiLayerTest.java analogues):
shapes, param counts, training convergence on toy data, param pack/unpack."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    InputType,
    NeuralNetConfiguration,
    OptimizationAlgorithm,
    Updater,
)
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction


def toy_classification(n=256, d=8, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, d)) * 3.0
    ys = rng.integers(0, classes, n)
    xs = centers[ys] + rng.normal(size=(n, d))
    labels = np.eye(classes)[ys]
    return DataSet(xs.astype(np.float32), labels.astype(np.float32))


def mlp_net(d=8, classes=3, updater=Updater.SGD, lr=0.1):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(7)
        .learning_rate(lr)
        .updater(updater)
        .list()
        .layer(0, L.DenseLayer(n_in=d, n_out=16, activation="relu"))
        .layer(1, L.OutputLayer(n_in=16, n_out=classes,
                                loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestBasics:
    def test_output_shapes(self):
        net = mlp_net()
        out = net.output(np.zeros((5, 8), np.float32))
        assert out.shape == (5, 3)
        np.testing.assert_allclose(np.sum(np.asarray(out), axis=1),
                                   np.ones(5), rtol=1e-5)

    def test_param_count(self):
        net = mlp_net()
        assert net.num_params() == 8 * 16 + 16 + 16 * 3 + 3

    def test_feed_forward_collects_activations(self):
        net = mlp_net()
        acts = net.feed_forward(np.zeros((4, 8), np.float32))
        assert len(acts) == 3  # input + 2 layers
        assert acts[1].shape == (4, 16)
        assert acts[2].shape == (4, 3)

    def test_param_roundtrip(self):
        net = mlp_net()
        flat = net.get_flat_params()
        assert flat.shape == (net.num_params(),)
        flat2 = flat + 1.0
        net.set_flat_params(flat2)
        np.testing.assert_allclose(net.get_flat_params(), flat2, rtol=1e-6)

    def test_param_table_names(self):
        net = mlp_net()
        table = net.get_param_table()
        assert set(table) == {"0_W", "0_b", "1_W", "1_b"}
        assert table["0_W"].shape == (8, 16)

    def test_deterministic_init(self):
        n1, n2 = mlp_net(), mlp_net()
        np.testing.assert_array_equal(n1.get_flat_params(), n2.get_flat_params())


class TestTraining:
    @pytest.mark.parametrize("updater", [
        Updater.SGD, Updater.ADAM, Updater.ADAGRAD, Updater.RMSPROP,
        Updater.NESTEROVS, Updater.ADADELTA,
    ])
    def test_score_decreases_all_updaters(self, updater):
        ds = toy_classification()
        lr = 0.5 if updater == Updater.ADADELTA else 0.05
        net = mlp_net(updater=updater, lr=lr)
        initial = net.score(ds)
        it = ListDataSetIterator(ds, batch_size=64)
        net.fit(it, num_epochs=20)
        final = net.score(ds)
        assert final < initial * 0.8, (updater, initial, final)

    def test_learns_toy_problem(self):
        ds = toy_classification()
        net = mlp_net(updater=Updater.ADAM, lr=0.01)
        it = ListDataSetIterator(ds, batch_size=64)
        net.fit(it, num_epochs=30)
        ev = net.evaluate(ds)
        assert ev.accuracy() > 0.9, ev.stats()

    def test_predict(self):
        ds = toy_classification(n=32)
        net = mlp_net()
        preds = net.predict(ds.features)
        assert preds.shape == (32,)
        assert preds.dtype.kind == "i"

    def test_fit_features_labels_signature(self):
        ds = toy_classification(n=64)
        net = mlp_net()
        net.fit(ds.features, ds.labels)
        assert np.isfinite(net.score_value)

    def test_listeners_fire(self):
        from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener

        ds = toy_classification(n=64)
        net = mlp_net()
        listener = CollectScoresIterationListener()
        net.set_listeners(listener)
        net.fit(ListDataSetIterator(ds, batch_size=32), num_epochs=2)
        assert len(listener.scores) == 4  # 2 batches × 2 epochs


class TestSolvers:
    @pytest.mark.parametrize("algo", [
        OptimizationAlgorithm.LINE_GRADIENT_DESCENT,
        OptimizationAlgorithm.CONJUGATE_GRADIENT,
        OptimizationAlgorithm.LBFGS,
    ])
    def test_full_batch_solvers_decrease_score(self, algo):
        ds = toy_classification(n=128)
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(7)
            .learning_rate(0.1)
            .iterations(15)
            .optimization_algo(algo)
            .list()
            .layer(0, L.DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=16, n_out=3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        initial = net.score(ds)
        net.fit(ds)
        assert net.score(ds) < initial * 0.7, (algo, initial, net.score(ds))


class TestMomentumSchedule:
    """momentumAfter parity (BaseUpdater.java:75-80): momentum switches
    STICKILY at each schedule iteration."""

    def test_nesterovs_matches_hand_rolled_sticky_switch(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.updater import (
            UpdaterSpec, apply_updater, init_updater_state)
        from deeplearning4j_tpu.nn.conf.enums import Updater as U
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer

        lc = DenseLayer(n_in=2, n_out=2, updater=U.NESTEROVS, momentum=0.9)
        spec = UpdaterSpec.from_layer_conf(
            lc, 0.1, momentum_schedule={2: 0.5, 4: 0.1})
        g = {"W": jnp.ones((2, 2))}
        state = init_updater_state(spec, g)
        # hand-rolled nd4j Nesterovs with the sticky switch
        v_ref, mus = np.zeros((2, 2)), []
        steps_ref = []
        for it in range(6):
            mu = 0.9 if it < 2 else (0.5 if it < 4 else 0.1)
            mus.append(mu)
            v_new = mu * v_ref - 0.1 * np.ones((2, 2))
            steps_ref.append(-(mu * v_new - 0.1 * np.ones((2, 2))))
            v_ref = v_new
        for it in range(6):
            steps, state = apply_updater(
                spec, g, state, jnp.asarray(1.0),
                jnp.asarray(it + 1))  # 1-based step ⇒ 0-based iteration
            np.testing.assert_allclose(np.asarray(steps["W"]),
                                       steps_ref[it], rtol=1e-6)

    def test_network_trains_with_schedule_and_serializes(self, tmp_path):
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
            .updater(Updater.NESTEROVS).momentum_after({3: 0.5})
            .list()
            .layer(0, L.DenseLayer(n_in=4, n_out=6, activation="tanh",
                                   momentum=0.9))
            .layer(1, L.OutputLayer(n_in=6, n_out=2))
            .build()
        )
        assert conf.global_conf.momentum_schedule == {3: 0.5}
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        ds = DataSet(rng.normal(size=(16, 4)).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)])
        for _ in range(6):
            net.fit(ds)
        assert np.isfinite(net.score_value)
        # the schedule survives native serde + the model zip
        from deeplearning4j_tpu.nn.conf.neural_net import (
            MultiLayerConfiguration)

        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.global_conf.momentum_schedule == {3: 0.5}
        ModelSerializer.write_model(net, str(tmp_path / "m.zip"))
        restored = ModelSerializer.restore(str(tmp_path / "m.zip"))
        assert restored.conf.global_conf.momentum_schedule == {3: 0.5}

    def test_reference_round_trip(self):
        from deeplearning4j_tpu.nn.conf.neural_net import (
            MultiLayerConfiguration)

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
            .updater(Updater.NESTEROVS).momentum_after({2: 0.25})
            .list()
            .layer(0, L.OutputLayer(n_in=4, n_out=2))
            .build()
        )
        back = MultiLayerConfiguration.from_reference_json(
            conf.to_reference_json())
        assert back.global_conf.momentum_schedule == {2: 0.25}


class TestDropoutAndRegularization:
    def test_l2_shrinks_weights(self):
        ds = toy_classification(n=128)
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(7).learning_rate(0.1).l2(0.5)
            .list()
            .layer(0, L.DenseLayer(n_in=8, n_out=16, activation="relu"))
            .layer(1, L.OutputLayer(n_in=16, n_out=3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        net_noreg = mlp_net(lr=0.1)
        it = ListDataSetIterator(ds, batch_size=64)
        net.fit(it, num_epochs=10)
        it2 = ListDataSetIterator(ds, batch_size=64)
        net_noreg.fit(it2, num_epochs=10)
        w_reg = np.linalg.norm(net.get_param_table()["0_W"])
        w_noreg = np.linalg.norm(net_noreg.get_param_table()["0_W"])
        assert w_reg < w_noreg

    def test_dropout_trains(self):
        ds = toy_classification(n=128)
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(7).learning_rate(0.05)
            .list()
            .layer(0, L.DenseLayer(n_in=8, n_out=32, activation="relu",
                                   dropout=0.5))
            .layer(1, L.OutputLayer(n_in=32, n_out=3))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        initial = net.score(ds)
        net.fit(ListDataSetIterator(ds, batch_size=64), num_epochs=15)
        assert net.score(ds) < initial


class TestFitSteps:
    """Fused multi-step driver (fit_steps) must match the per-step fit path."""

    def _net(self, seed=0):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
        from deeplearning4j_tpu.nn.conf import layers as L

        conf = (
            NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
            .updater(Updater.ADAM).list()
            .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=12, n_out=3))
            .build()
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        return MultiLayerNetwork(conf).init()

    def test_matches_stepwise_fit(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        x = rng.normal(size=(16, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        ds = DataSet(x, y)
        a, b = self._net(), self._net()
        for _ in range(5):
            a.fit(ds)
        b.fit_steps(ds, 5)
        assert b.iteration_count == 5
        np.testing.assert_allclose(a.get_flat_params(), b.get_flat_params(),
                                   rtol=1e-5, atol=1e-6)
        assert abs(a.score_value - b.score_value) < 1e-5

    def test_listener_fires_once(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresIterationListener)

        x = rng.normal(size=(8, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        net = self._net()
        lst = CollectScoresIterationListener()
        net.set_listeners(lst)
        net.fit_steps(DataSet(x, y), 7)
        assert [it for it, _ in lst.scores] == [7]

    def test_lbfgs_falls_back(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
            .optimization_algo(OptimizationAlgorithm.LBFGS).list()
            .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=8, n_out=2))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        net.fit_steps(DataSet(x, y), 2)  # falls back to fit loop
        assert np.isfinite(net.score_value)
