"""Serve fleet: routing policy, failover token identity, controller
eviction + straggler flagging, prefill/decode handoff, the virtual-clock
fleet driver, and the replica-kill chaos round.

The load-bearing claims:

1. **Failover never costs tokens.** A killed/wedged replica's requests
   complete on survivors with output token-identical to an unfailed run
   — greedy streams continue from their emitted prefix (prompt+prefix
   re-prefilled; prefill is deterministic), sampled streams replay from
   the original seed (the RNG chain is a pure function of the seed).
2. **Handoffs are exact.** A prefill replica's exported
   ``(kv_slab, cursor, rng_key)`` installed into a decode replica's
   free slot produces the same stream a local prefill would — greedy
   AND sampled.
3. **Routing is least-loaded and bounded.** Free-slots-minus-queue
   headroom first, TTFT tiebreak, spill on full queues, drop only when
   every alive replica is full; in-flight streams never migrate.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.monitor import metrics
from deeplearning4j_tpu.parallel.statetracker import InMemoryStateTracker
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.serving import (
    DecodeServer, ServeQueueFull, poisson_schedule, run_open_loop,
    serve_evict_s, serve_replicas, serve_role)
from deeplearning4j_tpu.serving.fleet import (
    FleetController, FleetLoadDriver, FleetRouter, ServeReplica,
    export_slot, install_slot, make_install)
from deeplearning4j_tpu.serving.fleet.handoff import SlotHandoff

_LM_CACHE = {}


def _lm(key="greedy", **kw):
    """One tiny model per config, cached for the module — fleet tests
    build many servers; the model (and its generate reference) should
    compile once."""
    if key not in _LM_CACHE:
        cfg = dict(vocab_size=61, d_model=32, num_heads=4,
                   num_kv_heads=2, num_layers=2, max_len=96, seed=3,
                   pos_encoding="rope")
        cfg.update(kw)
        _LM_CACHE[key] = TransformerLM(**cfg).init()
    return _LM_CACHE[key]


def _replica(rid, lm=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    return ServeReplica(rid, lm if lm is not None else _lm(), **kw)


def _ref(lm, prompt, n, **kw):
    return np.asarray(lm.generate(np.asarray(prompt)[None], n, **kw))[0]


# ---------------------------------------------------------------------------
# env knobs
# ---------------------------------------------------------------------------
class TestEnvKnobs:
    def test_serve_replicas(self, monkeypatch):
        assert serve_replicas() == 2
        monkeypatch.setenv("DL4J_SERVE_REPLICAS", "5")
        assert serve_replicas() == 5
        monkeypatch.setenv("DL4J_SERVE_REPLICAS", "junk")
        assert serve_replicas() == 2

    def test_serve_role(self, monkeypatch):
        assert serve_role() == "mixed"
        monkeypatch.setenv("DL4J_SERVE_ROLE", "prefill")
        assert serve_role() == "prefill"
        monkeypatch.setenv("DL4J_SERVE_ROLE", "bogus")
        with pytest.raises(ValueError, match="DL4J_SERVE_ROLE"):
            serve_role()

    def test_serve_evict_s(self, monkeypatch):
        assert serve_evict_s() == 10.0
        monkeypatch.setenv("DL4J_SERVE_EVICT_S", "2.5")
        assert serve_evict_s() == 2.5

    def test_replica_rejects_unknown_role(self):
        with pytest.raises(ValueError, match="role"):
            _replica("r0", role="bogus")


# ---------------------------------------------------------------------------
# server hooks: try_submit verdicts + free_slot_count
# ---------------------------------------------------------------------------
class TestAdmissionVerdict:
    def test_try_submit_and_free_slots(self):
        server = DecodeServer(_lm(), slots=2, max_len=64, max_queue=1)
        assert server.free_slot_count() == 2
        v1 = server.try_submit(np.arange(1, 5, dtype=np.int32), 4)
        assert v1.admitted and v1.request is not None
        assert v1.reason is None
        # queue bound 1: the second queued submit is a verdict, not a
        # raise; submit() keeps the raising semantics unchanged
        v2 = server.try_submit(np.arange(1, 5, dtype=np.int32), 2)
        assert not v2.admitted and v2.reason == "queue_full"
        assert v2.request is None and v2.queue_depth == 1
        with pytest.raises(ServeQueueFull):
            server.submit(np.arange(1, 5, dtype=np.int32), 2)
        # admission moves the free-slot count at the step boundary
        server.step()
        assert server.free_slot_count() == 1
        server.drain()
        assert server.free_slot_count() == 2
        # malformed requests still raise (caller bugs, not load)
        with pytest.raises(ValueError):
            server.try_submit(np.zeros(0, np.int32), 2)
        with pytest.raises(ValueError):
            server.try_submit(np.arange(1, 5, dtype=np.int32), 999)

    def test_rejected_counter_on_verdict(self):
        reg = metrics()
        server = DecodeServer(_lm(), slots=1, max_len=64, max_queue=1)
        r0 = reg.counter("serve_requests_total").value(event="rejected")
        server.try_submit(np.arange(1, 4, dtype=np.int32), 2)
        v = server.try_submit(np.arange(1, 4, dtype=np.int32), 2)
        assert not v.admitted
        assert reg.counter("serve_requests_total").value(
            event="rejected") == r0 + 1


# ---------------------------------------------------------------------------
# loadgen: per-drop timestamps
# ---------------------------------------------------------------------------
class TestLoadgenDrops:
    def test_drop_timestamps_recorded(self):
        server = DecodeServer(_lm(), slots=1, max_len=64, max_queue=1,
                              clock=time.monotonic)
        # rate so hot the 1-slot/1-deep server must shed
        sched = poisson_schedule(8, rate_rps=5000.0, vocab_size=61,
                                 prompt_lens=(4,), max_new_tokens=(8,),
                                 seed=0)
        report = run_open_loop(server, sched)
        assert report.rejected > 0
        assert len(report.drop_times_s) == report.rejected
        assert report.submitted + report.rejected == 8
        s = report.summary()
        assert s["dropped_request_seconds"] == sorted(
            round(t, 3) for t in report.drop_times_s)
        # open-loop semantics kept: drops are not retried
        assert report.finished == report.submitted


# ---------------------------------------------------------------------------
# routing policy
# ---------------------------------------------------------------------------
class TestRouterPlacement:
    def test_least_loaded_splits_a_burst(self):
        reps = [_replica(f"r{i}") for i in range(2)]
        router = FleetRouter(reps)
        a = router.submit(np.arange(1, 5, dtype=np.int32), 2)
        b = router.submit(np.arange(1, 5, dtype=np.int32), 2)
        # headroom counts queued work: the second request of a burst
        # must go to the other replica even before any step boundary
        assert {a.replica_id, b.replica_id} == {"r0", "r1"}

    def test_ttft_tiebreak(self):
        reps = [_replica(f"r{i}") for i in range(2)]
        reps[0]._ttfts.append(0.5)    # slow history
        reps[1]._ttfts.append(0.01)   # fast history
        router = FleetRouter(reps)
        fr = router.submit(np.arange(1, 5, dtype=np.int32), 2)
        assert fr.replica_id == "r1"

    def test_spill_and_drop(self):
        reg = metrics()
        reps = [_replica(f"r{i}", slots=1, max_queue=1)
                for i in range(2)]
        router = FleetRouter(reps)
        placed = [router.try_submit(np.arange(1, 4, dtype=np.int32), 2)
                  for _ in range(2)]
        assert {fr.replica_id for fr in placed} == {"r0", "r1"}
        d0 = reg.counter("serve_route_total").value(outcome="dropped")
        # both queues at their bound: the fleet sheds, no exception
        assert router.try_submit(
            np.arange(1, 4, dtype=np.int32), 2) is None
        assert reg.counter("serve_route_total").value(
            outcome="dropped") == d0 + 1

    def test_sticky_affinity(self):
        reps = [_replica(f"r{i}", slots=4, max_queue=8)
                for i in range(2)]
        router = FleetRouter(reps)
        a = router.submit(np.arange(1, 5, dtype=np.int32), 2,
                          affinity="session-7")
        # load the OTHER replica so least-loaded would pick it — the
        # affinity pin must win anyway
        other = "r1" if a.replica_id == "r0" else "r0"
        b = router.submit(np.arange(1, 5, dtype=np.int32), 2,
                          affinity="session-7")
        assert b.replica_id == a.replica_id != other
        # a dead pinned replica falls back to least-loaded
        router._by_id[a.replica_id].dead = True
        c = router.submit(np.arange(1, 5, dtype=np.int32), 2,
                          affinity="session-7")
        assert c.replica_id == other

    def test_build_reads_env_replica_count(self, monkeypatch):
        monkeypatch.setenv("DL4J_SERVE_REPLICAS", "3")
        router = FleetRouter.build(_lm(), slots=2, max_len=64)
        assert [r.replica_id for r in router.replicas] == [
            "replica-0", "replica-1", "replica-2"]
        assert router.build(_lm(), replicas=1, slots=2,
                            max_len=64).stats()["replicas"] == 1

    def test_uniform_pool_config_required(self):
        small = _replica("r1", max_len=48)
        with pytest.raises(ValueError, match="max_len"):
            FleetRouter([_replica("r0"), small])

    def test_uniform_temperature_required(self):
        hot = _replica("r1", server=DecodeServer(
            _lm(), slots=2, max_len=64, temperature=0.8))
        with pytest.raises(ValueError, match="temperature"):
            FleetRouter([_replica("r0"), hot])


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------
class TestFailover:
    def test_greedy_continuation_token_identity(self):
        lm = _lm()
        reps = [_replica(f"r{i}", slots=2, fuse_steps=2)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        prompt = np.arange(1, 7, dtype=np.int32)
        fr = router.submit(prompt, 8)
        victim = fr.replica_id
        router._by_id[victim].step_once()   # prefill + one fused pair
        emitted_before = len(fr.tokens)
        assert 0 < emitted_before < 8
        decision = controller.evict(victim, reason="test-kill")
        # the greedy continuation keeps the emitted prefix
        assert fr.emitted and len(fr.emitted) == emitted_before
        assert fr.replica_id != victim
        survivor = router._by_id[fr.replica_id]
        while survivor.busy():
            survivor.step_once()
        assert fr.finished
        assert np.array_equal(fr.output, _ref(lm, prompt, 8))
        # eviction evidence: decision in the log with the failover tally
        assert decision["replica"] == victim
        assert decision["failover"]["victims"] == 1
        assert controller.eviction_log[-1] is decision
        # the corpse's per-replica gauges are gone
        assert metrics().gauge("fleet_serve_occupancy").value(
            replica=victim) == 0.0

    def test_sampled_replay_token_identity(self):
        lm = _lm()
        reps = [ServeReplica(f"r{i}", lm, slots=2, max_len=64,
                             temperature=0.7, top_k=20)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        prompt = np.arange(1, 7, dtype=np.int32)
        fr = router.submit(prompt, 6, seed=123)
        victim = fr.replica_id
        router._by_id[victim].step_once()
        assert fr.tokens  # partial progress existed
        controller.evict(victim, reason="test-kill")
        # sampled streams replay from scratch: the prefix is discarded
        # (the RNG chain cannot resume mid-stream) and the full replay
        # is identical because the chain is a pure function of the seed
        assert fr.emitted == []
        survivor = router._by_id[fr.replica_id]
        while survivor.busy():
            survivor.step_once()
        assert fr.finished
        assert np.array_equal(
            fr.output, _ref(lm, prompt, 6, temperature=0.7, top_k=20,
                            seed=123))

    def test_exact_dispatch_counts_across_failover(self):
        """The dryrun smoke's arithmetic, asserted here too: K=4 fused,
        A needs 9 (prefill 1 + 4 on r0 before the kill, then re-prefill
        emits 1 + 3 fused on r1), B needs 5 (prefill 1 + 4 fused) — one
        shared dispatch on the survivor covers both."""
        lm = _lm()
        reps = [_replica(f"f{i}", slots=2, fuse_steps=4)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        prompt = np.arange(1, 9, dtype=np.int32)
        fa = router.submit(prompt, 9)
        fb = router.submit(prompt + 1, 5)
        assert fa.replica_id == "f0" and fb.replica_id == "f1"
        reps[0].step_once()
        assert len(fa.tokens) == 5
        controller.evict("f0", reason="test-kill")
        while reps[1].busy():
            reps[1].step_once()
        assert fa.finished and fb.finished
        assert reps[0].server.steps == 1 and reps[1].server.steps == 1
        assert np.array_equal(fa.output, _ref(lm, prompt, 9))
        assert np.array_equal(fb.output, _ref(lm, prompt + 1, 5))

    def test_fully_emitted_requeue_completes_without_survivor_work(self):
        """A max_new=1 split request whose handoff never installed: the
        prefill already emitted its one token, so eviction of the
        decode replica must complete the request in place — not strand
        it unfinished (the zero-lost contract) and not recompute it."""
        lm = _lm()
        pre = ServeReplica("p0", lm, role="prefill", slots=2,
                           max_len=64)
        dec = ServeReplica("d0", lm, role="decode", slots=2, max_len=64)
        router = FleetRouter([pre, dec])
        controller = FleetController(router, None, evict_timeout_s=5.0)
        prompt = np.arange(1, 6, dtype=np.int32)
        fr = router.submit(prompt, 1)
        pre.step_once()   # prefill done; handoff queued on d0, no step
        assert len(fr.tokens) == 1 and not fr.finished
        controller.evict("d0", reason="test-kill")
        assert fr.finished and fr.latency_s is not None
        assert np.array_equal(fr.output, _ref(lm, prompt, 1))

    def test_parked_failover_retries_when_survivor_frees(self):
        """Failover with every survivor full parks the victims; they
        must land (not be lost) once the survivor drains and the next
        tick retries."""
        lm = _lm()
        reps = [_replica(f"r{i}", slots=1, max_queue=1)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        frs = [router.submit(np.arange(1, 5, dtype=np.int32), 4, seed=i)
               for i in range(2)]
        for r in reps:
            r.step_once()   # queued -> live; queues free up again
        frs += [router.submit(np.arange(1, 5, dtype=np.int32), 4,
                              seed=2 + i) for i in range(2)]
        victim = frs[0].replica_id
        survivor = router._by_id["r1" if victim == "r0" else "r0"]
        controller.evict(victim, reason="test-kill")   # 2 victims; the
        # survivor is full (1 live + 1 queued) so they park
        assert router.stats()["pending_failover"] > 0
        for _ in range(64):
            if not router.unfinished():
                break
            survivor.step_once()
            controller.tick()   # the retry site real-time fleets use
        assert all(fr.finished for fr in frs), [fr.state for fr in frs]
        for fr in frs:
            assert np.array_equal(fr.output, _ref(lm, fr.prompt, 4))

    def test_queued_requests_requeue_too(self):
        lm = _lm()
        reps = [_replica(f"r{i}", slots=1, max_queue=4)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        frs = [router.submit(np.arange(1, 5, dtype=np.int32), 3, seed=i)
               for i in range(4)]
        victim = frs[0].replica_id
        controller.evict(victim, reason="test-kill")  # nothing stepped
        survivor = router._by_id[
            "r1" if victim == "r0" else "r0"]
        while survivor.busy():
            survivor.step_once()
        assert all(fr.finished for fr in frs)
        for fr in frs:
            assert np.array_equal(fr.output, _ref(lm, fr.prompt, 3))


# ---------------------------------------------------------------------------
# controller: gauges, stragglers, silence eviction
# ---------------------------------------------------------------------------
class TestController:
    def _fleet_of_three(self):
        # three replica handles over ONE shared server (cheap): the
        # controller only reads payloads in these tests, never steps
        shared = DecodeServer(_lm(), slots=2, max_len=64)
        reps = [ServeReplica(f"r{i}", _lm(), server=shared)
                for i in range(3)]
        return reps, FleetRouter(reps)

    def test_tick_gauges_from_payloads(self):
        reg = metrics()
        reps, router = self._fleet_of_three()
        controller = FleetController(router, None, evict_timeout_s=5.0)
        fleet = controller.tick()
        assert set(fleet) == {"r0", "r1", "r2"}
        assert reg.gauge("fleet_serve_replicas").value() == 3.0
        assert reg.gauge("fleet_serve_free_slots").value(
            replica="r1") == 2.0
        assert reg.gauge("fleet_serve_occupancy").value(
            replica="r2") == 0.0

    def test_straggler_flag_and_recovery(self):
        reg = metrics()
        reps, router = self._fleet_of_three()
        tracker = InMemoryStateTracker()
        controller = FleetController(router, tracker,
                                     evict_timeout_s=60.0,
                                     straggler_ratio=3.0)
        base = {"occupancy": 0.5, "queue_depth": 0, "free_slots": 1}
        tracker.heartbeat("r0", metrics={**base, "tpot_s": 0.01})
        tracker.heartbeat("r1", metrics={**base, "tpot_s": 0.012})
        tracker.heartbeat("r2", metrics={**base, "tpot_s": 0.2})
        c0 = reg.counter("fleet_serve_stragglers_total").value(
            replica="r2")
        controller.tick()
        assert controller.stragglers == {"r2"}
        assert reg.counter("fleet_serve_stragglers_total").value(
            replica="r2") == c0 + 1
        # recovery un-flags
        tracker.heartbeat("r2", metrics={**base, "tpot_s": 0.011})
        controller.tick()
        assert controller.stragglers == set()
        # below three reporting: no flags
        tracker2 = InMemoryStateTracker()
        tracker2.heartbeat("r0", metrics={**base, "tpot_s": 0.01})
        tracker2.heartbeat("r1", metrics={**base, "tpot_s": 9.9})
        controller2 = FleetController(router, tracker2,
                                      evict_timeout_s=60.0)
        controller2.tick()
        assert controller2.stragglers == set()

    def test_silence_eviction_with_evidence(self):
        reps, router = self._fleet_of_three()
        tracker = InMemoryStateTracker()
        controller = FleetController(router, tracker,
                                     evict_timeout_s=0.05)
        payload = {"occupancy": 1.0, "tpot_s": 0.02}
        for r in ("r0", "r1", "r2"):
            tracker.heartbeat(r, metrics=payload)
        time.sleep(0.08)
        tracker.heartbeat("r1", metrics=payload)
        tracker.heartbeat("r2", metrics=payload)
        controller.tick()
        assert controller.evicted == ["r0"]
        ev = controller.eviction_log[0]
        assert ev["reason"] == "heartbeat_silence"
        assert ev["silent_s"] >= 0.05
        assert ev["timeout_s"] == 0.05
        assert ev["last_metrics"]["occupancy"] == 1.0
        # an evicted replica is skipped by later ticks
        assert "r0" not in controller.tick()


# ---------------------------------------------------------------------------
# prefill/decode handoff
# ---------------------------------------------------------------------------
class TestHandoff:
    def test_export_install_round_trip_greedy(self):
        lm = _lm()
        import jax

        src = DecodeServer(lm, slots=2, max_len=64)
        dst = DecodeServer(lm, slots=2, max_len=64)
        prompt = np.arange(1, 9, dtype=np.int32)
        tok, key = src.engine.prefill(prompt, 0, jax.random.PRNGKey(0))
        slabs = export_slot(src.engine, 0)
        handoff = SlotHandoff(slabs=slabs, cursor=len(prompt),
                              key=np.asarray(key), first_token=int(tok),
                              kv_dtype=src.engine.kv_dtype,
                              max_len=src.engine.max_len)
        from deeplearning4j_tpu.serving.scheduler import ServeRequest

        req = ServeRequest(prompt=prompt, max_new_tokens=6)
        req.submit_s = 0.0
        req.tokens.append(int(tok))
        dst.admit_external(req, make_install(handoff))
        assert dst.busy()
        dst.drain()
        assert req.state == "finished"
        out = np.concatenate([prompt, np.asarray(req.tokens, np.int32)])
        assert np.array_equal(out, _ref(lm, prompt, 6))

    def test_split_fleet_end_to_end_sampled(self):
        lm = _lm()
        pre = ServeReplica("p0", lm, role="prefill", slots=2,
                           max_len=64, temperature=0.7, top_k=20)
        dec = ServeReplica("d0", lm, role="decode", slots=2,
                           max_len=64, temperature=0.7, top_k=20)
        router = FleetRouter([pre, dec])
        assert router.split
        prompt = np.arange(1, 7, dtype=np.int32)
        fr = router.submit(prompt, 6, seed=42)
        assert fr.replica_id == "p0"
        pre.step_once()
        # prefill stamped TTFT and the router moved it to the decoder
        assert fr.replica_id == "d0"
        assert len(fr.tokens) == 1 and fr.ttft_s is not None
        while dec.busy():
            dec.step_once()
        assert fr.finished
        assert np.array_equal(
            fr.output,
            _ref(lm, prompt, 6, temperature=0.7, top_k=20, seed=42))

    def test_split_fleet_config_and_capacity_validation(self):
        lm = _lm()
        # a speculative decode replica can never take handoffs: loud at
        # construction, not as a worker-thread death on first handoff
        pre = ServeReplica("p0", lm, role="prefill", slots=2,
                           max_len=64)
        spec_dec = ServeReplica("d0", lm, role="decode", server=(
            DecodeServer(lm, slots=2, max_len=64, draft_layers=1)))
        with pytest.raises(ValueError, match="speculative"):
            FleetRouter([pre, spec_dec])
        # oversized requests raise at submission like the mixed path,
        # instead of scattering past T_max on the decode side
        dec = ServeReplica("d0", lm, role="decode", slots=2, max_len=64)
        router = FleetRouter([pre, dec])
        with pytest.raises(ValueError, match="slot capacity"):
            router.submit(np.arange(1, 41, dtype=np.int32), 30)

    def test_handoff_validation(self):
        lm = _lm()
        import jax

        src = DecodeServer(lm, slots=2, max_len=64)
        prompt = np.arange(1, 5, dtype=np.int32)
        tok, key = src.engine.prefill(prompt, 0, jax.random.PRNGKey(0))
        slabs = export_slot(src.engine, 0)

        def handoff(**kw):
            base = dict(slabs=slabs, cursor=4, key=np.asarray(key),
                        first_token=int(tok),
                        kv_dtype=src.engine.kv_dtype,
                        max_len=src.engine.max_len)
            base.update(kw)
            return SlotHandoff(**base)

        wrong_len = DecodeServer(lm, slots=2, max_len=48)
        with pytest.raises(ValueError, match="max_len"):
            install_slot(wrong_len.engine, 0, handoff())
        with pytest.raises(ValueError, match="kv_dtype"):
            install_slot(
                DecodeServer(lm, slots=2, max_len=64,
                             kv_dtype="int8").engine, 0, handoff())
        # a speculative target has no draft-pool prompt K/V: reject
        spec = DecodeServer(lm, slots=2, max_len=64, draft_layers=1)
        from deeplearning4j_tpu.serving.scheduler import ServeRequest

        req = ServeRequest(prompt=prompt, max_new_tokens=2)
        req.tokens.append(int(tok))
        with pytest.raises(ValueError, match="speculative"):
            spec.admit_external(req, make_install(handoff()))
        # a request with no prefilled token is a protocol violation
        bare = ServeRequest(prompt=prompt, max_new_tokens=2)
        with pytest.raises(ValueError, match="prefilled"):
            DecodeServer(lm, slots=2, max_len=64).admit_external(
                bare, make_install(handoff()))


# ---------------------------------------------------------------------------
# virtual-clock driver
# ---------------------------------------------------------------------------
class TestVirtualDriver:
    def test_deterministic_scaling(self):
        """With a pinned per-step cost, 2 replicas under a saturating
        stream must finish in about half the single-replica wall — the
        arithmetic the bench's chip-per-replica model rides on."""
        def pinned_timer(replica):
            replica.step_once()
            return 0.01

        def run(n):
            reps = [_replica(f"r{i}", slots=2, fuse_steps=2)
                    for i in range(n)]
            router = FleetRouter(reps)
            driver = FleetLoadDriver(
                router, FleetController(router, None,
                                        evict_timeout_s=5.0),
                step_timer=pinned_timer)
            sched = poisson_schedule(12, rate_rps=1e4, vocab_size=61,
                                     prompt_lens=(4, 8),
                                     max_new_tokens=(6,), seed=5)
            report = driver.run(sched)
            assert report.finished == 12
            return report.summary()

        s1, s2 = run(1), run(2)
        scaling = s2["tokens_per_sec"] / s1["tokens_per_sec"]
        assert scaling > 1.6, scaling
        # queueing delay shrinks with capacity
        assert s2["p50_latency_ms"] < s1["p50_latency_ms"]

    def test_driver_failover_zero_lost(self):
        lm = _lm()

        def pinned_timer(replica):
            replica.step_once()
            return 0.01

        reps = [_replica(f"r{i}", slots=2, fuse_steps=2)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        driver = FleetLoadDriver(router, controller,
                                 step_timer=pinned_timer)
        sched = poisson_schedule(10, rate_rps=1e4, vocab_size=61,
                                 prompt_lens=(4,), max_new_tokens=(8,),
                                 seed=6)
        report = driver.run(sched, kill_at_s=0.02, kill_replica="r0")
        assert report.finished == 10  # zero lost
        assert controller.evicted == ["r0"]
        assert driver.kill_time_s is not None
        for fr in router.requests:
            assert np.array_equal(
                fr.output, _ref(lm, fr.prompt, fr.max_new_tokens))


# ---------------------------------------------------------------------------
# chaos: kill a live threaded replica mid-stream
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestReplicaKillChaos:
    def test_replica_death_mid_stream_completes_on_survivors(self):
        """The satellite chaos round: real threads, real heartbeats, a
        ``DL4J_FAULTS``-style injected death of one replica while its
        requests are in flight — every request must complete on the
        survivor with greedy token identity vs an unfailed run, and the
        controller log must carry the eviction evidence."""
        lm = _lm()
        tracker = InMemoryStateTracker()
        reps = [ServeReplica(f"r{i}", lm, tracker=tracker,
                             heartbeat_interval_s=0.05, slots=2,
                             max_len=64, fuse_steps=2)
                for i in range(2)]
        # warm the programs on this thread (jax tracing is not the
        # worker loop's job) and reset the bookkeeping
        for r in reps:
            r.server.submit(np.arange(1, 5, dtype=np.int32), 2)
            r.server.drain()
            r.server.finished.clear()
            r._finished_seen = 0
        router = FleetRouter(reps)
        controller = FleetController(router, tracker,
                                     evict_timeout_s=0.5)
        # queue the stream BEFORE the loops start, then kill r0 on its
        # 3rd loop iteration — it dies with work in flight
        frs = [router.submit(np.arange(1, 6, dtype=np.int32), 8, seed=i)
               for i in range(6)]
        on_r0 = [fr for fr in frs if fr.replica_id == "r0"]
        assert on_r0, "least-loaded routing should have used r0"
        try:
            faults.install("serve.replica.step.r0", faults.fail_nth(3))
            for r in reps:
                r.start()
            controller.start(interval_s=0.05)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(fr.finished for fr in frs):
                    break
                time.sleep(0.05)
        finally:
            faults.uninstall("serve.replica.step.r0")
            controller.stop()
            for r in reps:
                r.stop()
        assert all(fr.finished for fr in frs), [fr.state for fr in frs]
        assert reps[0].dead and "FaultInjected" in reps[0].dead_reason
        # zero lost + token identity (greedy) for EVERY request,
        # including the ones that failed over mid-stream
        for fr in frs:
            assert np.array_equal(fr.output, _ref(lm, fr.prompt, 8)), \
                fr.id
        evs = [e for e in controller.eviction_log
               if e["replica"] == "r0"]
        assert evs and evs[0]["reason"].startswith("crashed")
        assert evs[0]["failover"]["victims"] >= len(
            [fr for fr in on_r0 if fr.attempts > 1]) >= 0
