"""Recurrent ComputationGraph: TBPTT + stateful rnnTimeStep.

Models the reference's ComputationGraph RNN tests (TBPTT slicing
ComputationGraph.java:489-534, rnnTimeStep :1285; test strategy per
MultiLayerTestRNN / ComputationGraphTestRNN).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.enums import BackpropType
from deeplearning4j_tpu.nn.conf.graph import LastTimeStepVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.ops.losses import LossFunction


def _rnn_graph(vocab=12, hidden=8, seed=0, backprop_type=BackpropType.STANDARD,
               tbptt=8):
    g = (
        NeuralNetConfiguration.Builder()
        .seed(seed).learning_rate(0.01).updater(Updater.SGD)
        .graph_builder()
        .add_inputs("in")
        .add_layer("lstm", L.GravesLSTM(n_in=vocab, n_out=hidden,
                                        activation="tanh"), "in")
        .add_layer("out", L.RnnOutputLayer(
            n_in=hidden, n_out=vocab,
            loss_function=LossFunction.MCXENT), "lstm")
        .set_outputs("out")
        .backprop_type(backprop_type)
        .t_bptt_forward_length(tbptt)
        .t_bptt_backward_length(tbptt)
    )
    return ComputationGraph(g.build())


def _seq_data(batch=4, t=24, vocab=12, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, vocab, (batch, t))
    x = np.eye(vocab, dtype=np.float32)[idx]
    y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
    return DataSet(x, y)


class TestGraphTBPTT:
    def test_tbptt_window_iterations(self):
        net = _rnn_graph(backprop_type=BackpropType.TRUNCATED_BPTT,
                         tbptt=8).init()
        ds = _seq_data(t=24)
        net.fit(ds)
        # 24 steps in windows of 8 → 3 optimizer iterations
        assert net.iteration_count == 3
        assert np.isfinite(net.score_value)

    def test_single_window_tbptt_equals_standard(self):
        # window >= t → TBPTT must take the identical gradient step
        ds = _seq_data(t=12)
        std = _rnn_graph(seed=3).init()
        tb = _rnn_graph(seed=3, backprop_type=BackpropType.TRUNCATED_BPTT,
                        tbptt=12).init()
        std.fit(ds)
        tb.fit(ds)
        for k, v in std.get_param_table().items():
            np.testing.assert_allclose(tb.get_param_table()[k], v,
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=k)

    def test_tbptt_carries_state_across_windows(self):
        # two graphs, same params; one sees [0:8]+[8:16] as TBPTT windows,
        # the other is fed the windows as INDEPENDENT batches. If state
        # carries, parameters must diverge.
        ds = _seq_data(t=16, seed=5)
        tb = _rnn_graph(seed=7, backprop_type=BackpropType.TRUNCATED_BPTT,
                        tbptt=8).init()
        indep = _rnn_graph(seed=7).init()
        tb.fit(ds)
        x, y = np.asarray(ds.features), np.asarray(ds.labels)
        indep.fit(DataSet(x[:, :8], y[:, :8]))
        indep.fit(DataSet(x[:, 8:], y[:, 8:]))
        diffs = [
            float(np.max(np.abs(tb.get_param_table()[k]
                                - indep.get_param_table()[k])))
            for k in tb.get_param_table()
        ]
        assert max(diffs) > 1e-7, "TBPTT state did not carry across windows"


class TestGraphRnnTimeStep:
    def test_stepwise_matches_full_sequence(self):
        net = _rnn_graph(seed=1).init()
        ds = _seq_data(batch=3, t=10, seed=2)
        x = np.asarray(ds.features)
        full = np.asarray(net.output(x)[0])  # [b, t, vocab]

        net.rnn_clear_previous_state()
        steps = []
        for i in range(x.shape[1]):
            out = net.rnn_time_step(x[:, i, :])[0]  # 2D in → 2D out
            steps.append(np.asarray(out))
        stepped = np.stack(steps, axis=1)
        np.testing.assert_allclose(stepped, full, rtol=1e-5, atol=1e-6)

    def test_clear_state_resets(self):
        net = _rnn_graph(seed=1).init()
        x = np.asarray(_seq_data(batch=2, t=6, seed=3).features)
        first = np.asarray(net.rnn_time_step(x)[0])
        # carried state → different result on the same input
        second = np.asarray(net.rnn_time_step(x)[0])
        assert np.max(np.abs(second - first)) > 1e-6
        net.rnn_clear_previous_state()
        reset = np.asarray(net.rnn_time_step(x)[0])
        np.testing.assert_allclose(reset, first, rtol=1e-6, atol=1e-7)

    def test_recurrent_dag_with_last_time_step_vertex(self):
        # LSTM → LastTimeStep → OutputLayer: a recurrent DAG classifier
        vocab, hidden = 8, 6
        g = (
            NeuralNetConfiguration.Builder()
            .seed(0).learning_rate(0.05).updater(Updater.ADAM)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", L.GravesLSTM(n_in=vocab, n_out=hidden,
                                            activation="tanh"), "in")
            .add_vertex("last", LastTimeStepVertex("in"), "lstm")
            .add_layer("out", L.OutputLayer(
                n_in=hidden, n_out=3,
                loss_function=LossFunction.MCXENT), "last")
            .set_outputs("out")
        )
        net = ComputationGraph(g.build()).init()
        rng = np.random.default_rng(0)
        x = np.eye(vocab, dtype=np.float32)[rng.integers(0, vocab, (6, 10))]
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 6)]
        for _ in range(5):
            net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)
        out = np.asarray(net.output(x)[0])
        assert out.shape == (6, 3)


class TestFusedTBPTTStaticInput:
    """Fused TBPTT with a mixed static+temporal input graph: the 2D image
    input must be re-fed WHOLE to every scanned window while the sequence
    is sliced (the image-conditioning-a-caption-LSTM shape)."""

    @staticmethod
    def _captioner(seed):
        from deeplearning4j_tpu.nn.conf.graph import (
            DuplicateToTimeSeriesVertex, MergeVertex)

        vocab, hidden, img = 10, 8, 6
        g = (
            NeuralNetConfiguration.Builder()
            .seed(seed).learning_rate(0.01).updater(Updater.SGD)
            .graph_builder()
            .add_inputs("img", "seq")
            .add_layer("imgfeat", L.DenseLayer(n_in=img, n_out=4,
                                               activation="tanh"), "img")
            .add_vertex("dup", DuplicateToTimeSeriesVertex("seq"), "imgfeat")
            .add_vertex("cat", MergeVertex(), "seq", "dup")
            .add_layer("lstm", L.GravesLSTM(n_in=vocab + 4, n_out=hidden,
                                            activation="tanh"), "cat")
            .add_layer("out", L.RnnOutputLayer(
                n_in=hidden, n_out=vocab,
                loss_function=LossFunction.MCXENT), "lstm")
            .set_outputs("out")
            .backprop_type(BackpropType.TRUNCATED_BPTT)
            .t_bptt_forward_length(6)
            .t_bptt_backward_length(6)
        )
        return ComputationGraph(g.build())

    def test_fused_matches_window_loop(self):
        import jax
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet

        rng = np.random.default_rng(9)
        b, t, vocab, img = 3, 18, 10, 6
        idx = rng.integers(0, vocab, (b, t))
        seq = np.eye(vocab, dtype=np.float32)[idx]
        y = np.eye(vocab, dtype=np.float32)[np.roll(idx, -1, axis=1)]
        image = rng.random((b, img), np.float32)
        mds = MultiDataSet([image, seq], [y])

        fused = self._captioner(5).init()
        fused.fit(mds)  # 3 full windows → fused scan path
        assert fused.iteration_count == 3

        loop = self._captioner(5).init()
        from deeplearning4j_tpu.nn.graph import _slice_mds_time
        rnn_state = loop._zero_rnn_state(b)
        for start in range(0, t, 6):
            sub = _slice_mds_time(mds, start, start + 6)
            new_rnn = loop._one_iteration(sub, rnn_state)
            rnn_state = jax.tree_util.tree_map(
                jax.lax.stop_gradient, new_rnn)

        ft, lt = fused.get_param_table(), loop.get_param_table()
        for k in ft:
            np.testing.assert_allclose(ft[k], lt[k], rtol=1e-5, atol=1e-6,
                                       err_msg=k)
