"""ImageLSTM + utils (reference: nn/layers/recurrent/ImageLSTM.java,
util/ImageLoader.java, ArchiveUtils.java, DiskBasedQueue.java,
StringGrid.java, MathUtils.java)."""

import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.utils import (
    DiskBasedQueue,
    StringGrid,
    as_matrix,
    as_row_vector,
    decode_png,
    load_image,
    resize,
    save_pgm,
    unzip_file_to,
)
from deeplearning4j_tpu.utils import mathutils as mu


class TestImageLSTM:
    def _net(self):
        from deeplearning4j_tpu.nn.conf import (InputType,
                                                NeuralNetConfiguration,
                                                Updater)
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
            .updater(Updater.ADAM).list()
            .layer(0, L.ImageLSTM(n_in=6, n_out=5, hidden_size=8))
            .layer(1, L.RnnOutputLayer(n_in=5, n_out=5))
            .set_input_type(InputType.recurrent(6))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    def test_forward_shapes_and_training(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet

        net = self._net()
        x = rng.normal(size=(4, 7, 6)).astype(np.float32)
        y = np.zeros((4, 7, 5), np.float32)
        y[..., 0] = 1.0
        out = np.asarray(net.output(x))
        assert out.shape == (4, 7, 5)
        s0 = None
        for _ in range(20):
            net.fit(DataSet(x, y))
            s0 = s0 or net.score_value
        assert net.score_value < s0

    def test_conf_roundtrip(self):
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.layers import LayerConf

        lc = L.ImageLSTM(n_in=6, n_out=5, hidden_size=8)
        again = LayerConf.from_dict(lc.to_dict())
        assert isinstance(again, L.ImageLSTM) and again.hidden_size == 8

    def test_beam_search_decodes(self, rng):
        net = self._net()
        impl = net.layers[0]
        params = net.params["0"]
        xi = rng.normal(size=(6,)).astype(np.float32)
        ws = rng.normal(size=(5, 6)).astype(np.float32)  # token → input vec
        results = impl.beam_search(params, xi, ws, n_steps=4, beam_width=2)
        assert results, "beam search returned nothing"
        tokens, logp = results[0]
        assert len(tokens) == 4
        assert all(0 <= t < 5 for t in tokens)
        assert logp <= 0  # log-prob
        # scores sorted best-first
        scores = [lp for _, lp in results]
        assert scores == sorted(scores, reverse=True)

    def test_beam_search_end_token(self, rng):
        net = self._net()
        impl = net.layers[0]
        results = impl.beam_search(
            net.params["0"], rng.normal(size=(6,)).astype(np.float32),
            rng.normal(size=(5, 6)).astype(np.float32),
            n_steps=8, beam_width=3, end_token=0)
        for tokens, _ in results:
            if 0 in tokens:
                assert tokens[-1] == 0 or 0 not in tokens[:-1]

    def test_masking_holds_state(self, rng):
        """Masked trailing steps must not change the final unmasked output."""
        import jax.numpy as jnp

        net = self._net()
        impl = net.layers[0]
        p = net.params["0"]
        x3 = rng.normal(size=(2, 3, 6)).astype(np.float32)
        x5 = np.concatenate(
            [x3, rng.normal(size=(2, 2, 6)).astype(np.float32)], axis=1)
        mask = np.array([[1, 1, 1, 0, 0]] * 2, np.float32)
        y3, _ = impl.forward(p, jnp.asarray(x3), {})
        y5, _ = impl.forward(p, jnp.asarray(x5), {}, mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(y3), np.asarray(y5)[:, :3],
                                   atol=1e-6)


class TestImageIO:
    def test_png_roundtrip_gray(self, tmp_path):
        from deeplearning4j_tpu.ui.listeners import encode_png_gray

        img = (np.arange(48).reshape(6, 8) * 5).astype(np.uint8)
        png = encode_png_gray(img)
        decoded = decode_png(png)
        np.testing.assert_array_equal(decoded, img)

    def test_pgm_roundtrip_and_loaders(self, tmp_path):
        img = (np.arange(24).reshape(4, 6) * 10).astype(np.uint8)
        p = str(tmp_path / "img.pgm")
        save_pgm(p, img)
        loaded = load_image(p)
        np.testing.assert_array_equal(loaded, img)
        m = as_matrix(p)
        assert m.dtype == np.float32 and m.max() <= 1.0
        assert as_row_vector(p).shape == (24,)

    def test_resize_nearest(self):
        img = np.arange(16).reshape(4, 4)
        small = resize(img, 2, 2)
        assert small.shape == (2, 2)
        assert small[0, 0] == img[0, 0]
        big = resize(img, 8, 8)
        assert big.shape == (8, 8)

    def test_unsupported_format(self, tmp_path):
        p = tmp_path / "x.bin"
        p.write_bytes(b"garbage")
        with pytest.raises(ValueError):
            load_image(str(p))


class TestArchive:
    def test_unzip(self, tmp_path):
        z = tmp_path / "a.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("inner/data.txt", "hello")
        dest = tmp_path / "out"
        unzip_file_to(str(z), str(dest))
        assert (dest / "inner" / "data.txt").read_text() == "hello"

    def test_tar_gz(self, tmp_path):
        import tarfile

        src = tmp_path / "f.txt"
        src.write_text("content")
        t = tmp_path / "a.tar.gz"
        with tarfile.open(t, "w:gz") as tf:
            tf.add(src, arcname="f.txt")
        dest = tmp_path / "out2"
        unzip_file_to(str(t), str(dest))
        assert (dest / "f.txt").read_text() == "content"

    def test_zip_slip_rejected(self, tmp_path):
        z = tmp_path / "evil.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("../escape.txt", "bad")
        with pytest.raises(ValueError):
            unzip_file_to(str(z), str(tmp_path / "out3"))


class TestDiskQueue:
    def test_fifo_and_drain(self, tmp_path):
        with DiskBasedQueue(str(tmp_path / "q")) as q:
            assert q.poll() is None and q.is_empty()
            for i in range(5):
                q.add({"i": i})
            assert q.size() == 5
            assert q.poll()["i"] == 0
            rest = [x["i"] for x in q.drain()]
            assert rest == [1, 2, 3, 4]
            assert q.is_empty()

    def test_close_cleans_dir(self, tmp_path):
        d = str(tmp_path / "q2")
        q = DiskBasedQueue(d)
        q.add(1)
        q.close()
        assert not os.path.exists(d)


class TestStringGrid:
    def _grid(self):
        return StringGrid.from_input(
            ["a,1,x", "b,2,", "a,1,x", "c,3,z"], sep=",")

    def test_accessors(self):
        g = self._grid()
        assert g.num_rows() == 4 and g.num_columns() == 3
        assert g.get_column(0) == ["a", "b", "a", "c"]
        assert g.get_row(1) == ["b", "2", ""]

    def test_transforms(self):
        g = self._grid()
        assert g.dedupe_rows().num_rows() == 3
        assert g.remove_rows_with_empty_column(2).num_rows() == 3
        assert g.filter_by_value(0, "a").num_rows() == 2
        assert g.sort_by_column(0, reverse=True).get_column(0)[0] == "c"
        assert g.select_columns([2, 0]).get_row(0) == ["x", "a"]
        g2 = g.append_column(["p", "q", "r", "s"])
        assert g2.num_columns() == 4
        with pytest.raises(ValueError):
            g.append_column(["only-one"])

    def test_file_roundtrip(self, tmp_path):
        g = self._grid()
        p = str(tmp_path / "grid.csv")
        g.write_file(p)
        assert StringGrid.from_file(p, ",") == g

    def test_ragged_rejected(self):
        with pytest.raises(ValueError):
            StringGrid(",", [["a"], ["b", "c"]])


class TestMathUtils:
    def test_normalize_and_clamp(self):
        assert mu.normalize(5, 0, 10) == 0.5
        with pytest.raises(ValueError):
            mu.normalize(1, 2, 2)
        assert mu.clamp(11, 0, 10) == 10
        out = mu.normalize_array([1, 2, 3], 0, 1)
        np.testing.assert_allclose(out, [0, 0.5, 1])

    def test_entropy_and_gain(self):
        assert mu.entropy([0.5, 0.5]) == pytest.approx(1.0)
        assert mu.entropy([1.0]) == 0.0
        gain = mu.information_gain([8, 8], [[8, 0], [0, 8]])
        assert gain == pytest.approx(1.0)  # perfect split

    def test_regression_stats(self):
        a = [1.0, 2.0, 3.0]
        assert mu.ss_error(a, a) == 0.0
        assert mu.correlation([1, 2, 3], [2, 4, 6]) == pytest.approx(1.0)
        assert mu.correlation([1, 2, 3], [3, 2, 1]) == pytest.approx(-1.0)
        assert mu.correlation([1, 1, 1], [1, 2, 3]) == 0.0
        assert mu.sum_of_products([1, 2], [3, 4]) == 11.0

    def test_discretize_and_powers(self):
        assert mu.discretize(0.0, 0, 1, 4) == 0
        assert mu.discretize(1.0, 0, 1, 4) == 3
        assert mu.next_power_of_2(5) == 8
        assert mu.next_power_of_2(1) == 1
        assert mu.round_to_decimals(1.23456, 2) == 1.23

    def test_misc(self):
        assert mu.sigmoid(0.0) == 0.5
        assert mu.sigmoid(-700) == pytest.approx(0.0, abs=1e-300)
        assert mu.bernoullis(1, 2, 0.5) == pytest.approx(0.5)
        assert mu.combination(5, 2) == 10
        w = mu.weights_for([10, 1])
        assert w.sum() == pytest.approx(1.0) and w[1] > w[0]


class TestPlotFilters:
    def test_dense_and_conv_grids(self, rng):
        from deeplearning4j_tpu.plot import filters_grid, render_to_png

        dense = rng.normal(size=(9, 6))
        g = filters_grid(dense)
        assert g.dtype == np.uint8 and g.ndim == 2
        conv = rng.normal(size=(5, 5, 3, 8))
        g2 = filters_grid(conv)
        # 8 filters → 3x3 grid of 5px tiles with 1px padding
        assert g2.shape == (3 * 6 - 1, 3 * 6 - 1)
        png = render_to_png(conv)
        assert png[:8] == b"\x89PNG\r\n\x1a\n"
        with pytest.raises(ValueError):
            filters_grid(rng.normal(size=(3,)))

    def test_render_layer(self, rng):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.plot import render_layer

        conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
                .list()
                .layer(0, L.DenseLayer(n_in=16, n_out=4))
                .layer(1, L.OutputLayer(n_in=4, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        png = render_layer(net, 0)
        assert png[:8] == b"\x89PNG\r\n\x1a\n"
        with pytest.raises(KeyError):
            render_layer(net, 9)


class TestReconstructionIterator:
    def test_labels_become_features(self, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterator import (
            ListDataSetIterator, ReconstructionDataSetIterator)

        x = rng.normal(size=(20, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 20)]
        it = ReconstructionDataSetIterator(
            ListDataSetIterator(DataSet(x, y), 8))
        ds = it.next()
        np.testing.assert_array_equal(ds.features, ds.labels)
        assert it.total_outcomes() == 5
        n = ds.num_examples()
        while it.has_next():
            n += it.next().num_examples()
        assert n == 20
        it.reset()
        assert it.has_next()
