"""Reference-format JSON compat loader + real YAML serde + sampling/
composable preprocessors (SURVEY hard-part #7; reference serde contract
NeuralNetConfiguration.java:214-239)."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater, WeightInit
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.enums import BackpropType, PoolingType
from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.preprocessors import (
    BinomialSamplingPreProcessor,
    CnnToFeedForwardPreProcessor,
    ComposableInputPreProcessor,
    RnnToFeedForwardPreProcessor,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction


# Hand-built to the reference's Jackson conventions: WRAPPER_OBJECT layer
# tags (Layer.java:44-59), camelCase fields, Java enum names.
REFERENCE_LENET_JSON = json.dumps({
    "backprop": True,
    "pretrain": False,
    "backpropType": "Standard",
    "tbpttFwdLength": 20,
    "tbpttBackLength": 20,
    "inputPreProcessors": {
        "4": {"cnnToFeedForward":
              {"inputHeight": 4, "inputWidth": 4, "numChannels": 12}}
    },
    "confs": [
        {"layer": {"convolution": {
            "nIn": 1, "nOut": 6, "kernelSize": [5, 5], "stride": [1, 1],
            "padding": [0, 0], "activationFunction": "relu",
            "weightInit": "XAVIER", "updater": "ADAM",
            "learningRate": 0.01, "l2": 1e-4, "dropOut": 0.0}},
         "numIterations": 1, "seed": 42, "miniBatch": True,
         "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT",
         "learningRatePolicy": "None"},
        {"layer": {"subsampling": {
            "poolingType": "MAX", "kernelSize": [2, 2], "stride": [2, 2],
            "padding": [0, 0]}},
         "numIterations": 1, "seed": 42},
        {"layer": {"convolution": {
            "nIn": 6, "nOut": 12, "kernelSize": [3, 3], "stride": [1, 1],
            "padding": [0, 0], "activationFunction": "relu",
            "updater": "ADAM", "learningRate": 0.01}},
         "numIterations": 1, "seed": 42},
        {"layer": {"subsampling": {
            "poolingType": "MAX", "kernelSize": [2, 2], "stride": [2, 2],
            "padding": [0, 0]}},
         "numIterations": 1, "seed": 42},
        {"layer": {"dense": {
            "nIn": 192, "nOut": 32, "activationFunction": "relu",
            "weightInit": "XAVIER", "updater": "ADAM",
            "learningRate": 0.01}},
         "numIterations": 1, "seed": 42},
        {"layer": {"output": {
            "nIn": 32, "nOut": 10, "activationFunction": "softmax",
            "lossFunction": "MCXENT", "weightInit": "XAVIER",
            "updater": "ADAM", "learningRate": 0.01}},
         "numIterations": 1, "seed": 42},
    ],
})


class TestReferenceJsonLoader:
    def test_layer_translation(self):
        conf = MultiLayerConfiguration.from_reference_json(
            REFERENCE_LENET_JSON)
        kinds = [type(l).__name__ for l in conf.layers]
        assert kinds == ["ConvolutionLayer", "SubsamplingLayer",
                        "ConvolutionLayer", "SubsamplingLayer",
                        "DenseLayer", "OutputLayer"]
        c0 = conf.layers[0]
        assert (c0.n_in, c0.n_out) == (1, 6)
        assert c0.kernel_size == (5, 5)
        assert c0.activation == "relu"
        assert c0.weight_init == WeightInit.XAVIER
        assert c0.updater == Updater.ADAM
        assert c0.l2 == pytest.approx(1e-4)
        assert conf.layers[1].pooling_type == PoolingType.MAX
        assert conf.layers[5].loss_function == LossFunction.MCXENT
        assert conf.global_conf.seed == 42
        assert conf.global_conf.learning_rate == pytest.approx(0.01)
        assert conf.backprop_type == BackpropType.STANDARD
        pre = conf.input_preprocessors[4]
        assert isinstance(pre, CnnToFeedForwardPreProcessor)
        assert (pre.height, pre.width, pre.channels) == (4, 4, 12)

    def test_loaded_network_trains(self):
        conf = MultiLayerConfiguration.from_reference_json(
            REFERENCE_LENET_JSON)
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.random((8, 24, 24, 1), np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)]
        ds = DataSet(x, y)
        net.fit(ds)
        s0 = net.score(ds)
        for _ in range(5):
            net.fit(ds)
        assert net.score(ds) < s0

    def test_lstm_tbptt_document(self):
        doc = json.dumps({
            "backprop": True, "backpropType": "TruncatedBPTT",
            "tbpttFwdLength": 8, "tbpttBackLength": 8,
            "confs": [
                {"layer": {"gravesLSTM": {
                    "nIn": 10, "nOut": 16, "activationFunction": "tanh",
                    "updater": "ADAM", "learningRate": 0.02}},
                 "seed": 7, "numIterations": 1},
                {"layer": {"rnnoutput": {
                    "nIn": 16, "nOut": 10, "activationFunction": "softmax",
                    "lossFunction": "MCXENT", "updater": "ADAM",
                    "learningRate": 0.02}},
                 "seed": 7, "numIterations": 1},
            ],
        })
        conf = MultiLayerConfiguration.from_reference_json(doc)
        assert conf.backprop_type == BackpropType.TRUNCATED_BPTT
        assert conf.tbptt_fwd_length == 8
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(1)
        idx = rng.integers(0, 10, (4, 16))
        x = np.eye(10, dtype=np.float32)[idx]
        y = np.eye(10, dtype=np.float32)[np.roll(idx, -1, axis=1)]
        net.fit(DataSet(x, y))
        assert net.iteration_count == 2  # two fused TBPTT windows
        assert np.isfinite(net.score_value)

    def test_distribution_and_unknown_fields_tolerated(self):
        doc = json.dumps({
            "backprop": True,
            "confs": [{
                "layer": {"dense": {
                    "nIn": 4, "nOut": 3, "activationFunction": "tanh",
                    "weightInit": "DISTRIBUTION",
                    "dist": {"normal": {"mean": 0.0, "std": 0.5}},
                    "momentum": 0.9, "someFutureField": 1}},
                "seed": 1}],
        })
        conf = MultiLayerConfiguration.from_reference_json(doc)
        d = conf.layers[0]
        assert d.weight_init == WeightInit.DISTRIBUTION
        assert d.dist == {"type": "normal", "mean": 0.0, "std": 0.5}
        assert d.momentum == pytest.approx(0.9)

    def test_composable_and_binomial_preprocessor_documents(self):
        doc = json.dumps({
            "backprop": True,
            "inputPreProcessors": {
                "0": {"binomialSampling": {}},
                "1": {"composableInput": {"inputPreProcessors": [
                    {"rnnToFeedForward": {}},
                    {"zeroMean": {}},
                ]}},
            },
            "confs": [
                {"layer": {"dense": {"nIn": 6, "nOut": 5,
                                     "activationFunction": "relu"}},
                 "seed": 1},
                {"layer": {"output": {"nIn": 5, "nOut": 2,
                                      "lossFunction": "MCXENT"}},
                 "seed": 1},
            ],
        })
        conf = MultiLayerConfiguration.from_reference_json(doc)
        assert isinstance(conf.input_preprocessors[0],
                          BinomialSamplingPreProcessor)
        comp = conf.input_preprocessors[1]
        assert isinstance(comp, ComposableInputPreProcessor)
        assert isinstance(comp.preprocessors[0], RnnToFeedForwardPreProcessor)

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            MultiLayerConfiguration.from_reference_json("{}")
        with pytest.raises(ValueError, match="unknown reference layer"):
            MultiLayerConfiguration.from_reference_json(json.dumps(
                {"confs": [{"layer": {"frobnicator": {}}}]}))


class TestYamlSerde:
    def _conf(self):
        return (
            NeuralNetConfiguration.Builder()
            .seed(3).learning_rate(0.02).updater(Updater.ADAM)
            .list()
            .layer(0, L.DenseLayer(n_in=7, n_out=5, activation="relu",
                                   l2=1e-4))
            .layer(1, L.OutputLayer(n_in=5, n_out=3,
                                    loss_function=LossFunction.MCXENT))
            .build()
        )

    def test_yaml_round_trip(self):
        conf = self._conf()
        text = conf.to_yaml()
        assert ":" in text and "{" not in text.splitlines()[0]  # block style
        back = MultiLayerConfiguration.from_yaml(text)
        assert back == conf

    def test_yaml_is_not_json(self):
        text = self._conf().to_yaml()
        with pytest.raises(json.JSONDecodeError):
            json.loads(text)

    def test_from_yaml_accepts_json(self):
        conf = self._conf()
        assert MultiLayerConfiguration.from_yaml(conf.to_json()) == conf

    def test_graph_yaml_round_trip(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)

        g = (
            NeuralNetConfiguration.Builder()
            .seed(0).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", L.DenseLayer(n_in=4, n_out=3), "in")
            .add_layer("out", L.OutputLayer(
                n_in=3, n_out=2, loss_function=LossFunction.MCXENT), "d")
            .set_outputs("out")
        )
        conf = g.build()
        back = ComputationGraphConfiguration.from_yaml(conf.to_yaml())
        assert back == conf


class TestSamplingPreprocessors:
    def test_binomial_sampling_forward_and_grad(self):
        import jax
        import jax.numpy as jnp

        p = BinomialSamplingPreProcessor()
        x = jnp.full((64, 32), 0.7)
        out = p.pre_process(x, rng=jax.random.PRNGKey(0))
        vals = np.unique(np.asarray(out))
        assert set(vals.tolist()) <= {0.0, 1.0}
        assert abs(float(out.mean()) - 0.7) < 0.1

        # straight-through gradient: identity backprop (reference parity)
        g = jax.grad(lambda v: p.pre_process(
            v, rng=jax.random.PRNGKey(1)).sum())(x)
        np.testing.assert_allclose(np.asarray(g), 1.0)

    def test_composable_chains_and_infers_types(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.inputs import InputType

        comp = ComposableInputPreProcessor(preprocessors=(
            RnnToFeedForwardPreProcessor(),
        ))
        x = jnp.ones((2, 5, 3))
        assert comp.pre_process(x, batch=2).shape == (10, 3)
        t = comp.output_type(InputType.recurrent(3, 5))
        assert t.kind == "FF"

    def test_composable_serde_round_trip(self):
        from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor

        comp = ComposableInputPreProcessor(preprocessors=(
            RnnToFeedForwardPreProcessor(),
            BinomialSamplingPreProcessor(),
        ))
        back = InputPreProcessor.from_dict(comp.to_dict())
        assert isinstance(back, ComposableInputPreProcessor)
        assert [type(p).__name__ for p in back.preprocessors] == [
            "RnnToFeedForwardPreProcessor", "BinomialSamplingPreProcessor"]

    def test_binomial_in_network_trains(self):
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(0).learning_rate(0.05)
            .list()
            .layer(0, L.DenseLayer(n_in=12, n_out=8, activation="relu"))
            .layer(1, L.OutputLayer(n_in=8, n_out=2,
                                    loss_function=LossFunction.MCXENT))
            .input_pre_processor(0, BinomialSamplingPreProcessor())
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.random((16, 12), np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)


class TestYamlEdgeCases:
    def test_quoted_colon_string_in_sequence(self):
        from deeplearning4j_tpu.utils import yamlio

        doc = {"names": ["conv: 1", "plain", 'quo"te'], "n": 3}
        assert yamlio.load(yamlio.dump(doc)) == doc

    def test_nan_inf_strings_stay_strings(self):
        from deeplearning4j_tpu.utils import yamlio

        doc = {"name": "nan", "other": "Infinity", "real": 1.5}
        back = yamlio.load(yamlio.dump(doc))
        assert back == doc and isinstance(back["name"], str)

    def test_empty_collections_in_sequences(self):
        from deeplearning4j_tpu.utils import yamlio

        doc = {"xs": [[], {}, [1], {"a": 1}, "[]"],
               "empty_list": [], "empty_map": {}}
        assert yamlio.load(yamlio.dump(doc)) == doc


REFERENCE_GRAPH_JSON = json.dumps({
    # Hand-built to ComputationGraphConfiguration.toJson() conventions:
    # Jackson field names (ComputationGraphConfiguration.java:59-81) and
    # GraphVertex WRAPPER_OBJECT tags (nn/conf/graph/GraphVertex.java:37-44).
    "vertices": {
        "d1": {"LayerVertex": {"layerConf": {
            "layer": {"dense": {"nIn": 8, "nOut": 6,
                                "activationFunction": "relu",
                                "weightInit": "XAVIER", "updater": "ADAM",
                                "learningRate": 0.05}},
            "seed": 11, "numIterations": 1}}},
        "d2": {"LayerVertex": {"layerConf": {
            "layer": {"dense": {"nIn": 8, "nOut": 6,
                                "activationFunction": "relu",
                                "updater": "ADAM", "learningRate": 0.05}},
            "seed": 11, "numIterations": 1}}},
        "ew": {"ElementWiseVertex": {"op": "Add"}},
        "lstm": {"LayerVertex": {"layerConf": {
            "layer": {"gravesLSTM": {"nIn": 4, "nOut": 6,
                                     "activationFunction": "tanh",
                                     "updater": "ADAM",
                                     "learningRate": 0.05}},
            "seed": 11, "numIterations": 1}}},
        "last": {"LastTimeStepVertex": {"maskArrayInputName": "seq"}},
        "dup": {"DuplicateToTimeSeriesVertex": {"inputName": "seq"}},
        "rnnout": {"LayerVertex": {"layerConf": {
            "layer": {"rnnoutput": {"nIn": 6, "nOut": 2,
                                    "activationFunction": "softmax",
                                    "lossFunction": "MCXENT",
                                    "updater": "ADAM",
                                    "learningRate": 0.05}},
            "seed": 11, "numIterations": 1}}},
        "merge": {"MergeVertex": {}},
        "sub": {"SubsetVertex": {"from": 0, "to": 9}},
        "out": {"LayerVertex": {"layerConf": {
            "layer": {"output": {"nIn": 10, "nOut": 3,
                                 "activationFunction": "softmax",
                                 "lossFunction": "MCXENT",
                                 "updater": "ADAM", "learningRate": 0.05}},
            "seed": 11, "numIterations": 1}}},
    },
    "vertexInputs": {
        "d1": ["in"], "d2": ["in"], "ew": ["d1", "d2"],
        "lstm": ["seq"], "last": ["lstm"],
        "dup": ["ew"], "rnnout": ["dup"],
        "merge": ["ew", "last"], "sub": ["merge"], "out": ["sub"],
    },
    "networkInputs": ["in", "seq"],
    "networkOutputs": ["out", "rnnout"],
    "pretrain": False, "backprop": True,
    "backpropType": "Standard",
    "tbpttFwdLength": 20, "tbpttBackLength": 20,
})


class TestReferenceGraphJsonLoader:
    """Reference ComputationGraphConfiguration.toJson() compat
    (ComputationGraphConfiguration.java:113,129; GraphVertex.java:37-44)."""

    def _load(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)

        return ComputationGraphConfiguration.from_reference_json(
            REFERENCE_GRAPH_JSON)

    def test_structure_translation(self):
        from deeplearning4j_tpu.nn.conf import graph as G

        conf = self._load()
        assert conf.inputs == ["in", "seq"]
        assert conf.outputs == ["out", "rnnout"]
        assert set(conf.layers) == {"d1", "d2", "lstm", "rnnout", "out"}
        assert isinstance(conf.vertices["merge"], G.MergeVertex)
        ew = conf.vertices["ew"]
        assert isinstance(ew, G.ElementWiseVertex) and ew.op == "Add"
        sub = conf.vertices["sub"]
        assert (sub.from_index, sub.to_index) == (0, 9)
        last = conf.vertices["last"]
        assert isinstance(last, G.LastTimeStepVertex)
        assert last.mask_input == "seq"
        dup = conf.vertices["dup"]
        assert isinstance(dup, G.DuplicateToTimeSeriesVertex)
        assert dup.input_name == "seq"
        assert conf.vertex_inputs["merge"] == ["ew", "last"]
        assert conf.global_conf.seed == 11
        assert conf.global_conf.learning_rate == pytest.approx(0.05)
        # round-trips through our native serde unchanged
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        assert ComputationGraphConfiguration.from_json(conf.to_json()) == conf

    def test_loaded_graph_trains_and_outputs(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = self._load()
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        x = rng.random((4, 8), np.float32)
        seq = rng.random((4, 5, 4), np.float32)
        y0 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)]
        y1 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 5))]
        net.fit([x, seq], [y0, y1])
        s0 = net.score_value
        for _ in range(5):
            net.fit([x, seq], [y0, y1])
        assert np.isfinite(net.score_value) and net.score_value < s0
        outs = net.output(x, seq)
        assert outs[0].shape == (4, 3)
        assert outs[1].shape == (4, 5, 2)

    def test_layer_vertex_preprocessor(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)

        doc = json.dumps({
            "vertices": {
                "d": {"LayerVertex": {
                    "layerConf": {"layer": {"dense": {
                        "nIn": 192, "nOut": 10,
                        "activationFunction": "relu"}}, "seed": 1},
                    "preProcessor": {"cnnToFeedForward": {
                        "inputHeight": 4, "inputWidth": 4,
                        "numChannels": 12}}}},
                "out": {"LayerVertex": {"layerConf": {
                    "layer": {"output": {"nIn": 10, "nOut": 2,
                                         "lossFunction": "MCXENT"}},
                    "seed": 1}}},
            },
            "vertexInputs": {"d": ["in"], "out": ["d"]},
            "networkInputs": ["in"],
            "networkOutputs": ["out"],
        })
        conf = ComputationGraphConfiguration.from_reference_json(doc)
        pre = conf.preprocessors["d"]
        assert isinstance(pre, CnnToFeedForwardPreProcessor)
        assert (pre.height, pre.width, pre.channels) == (4, 4, 12)

    def test_rejects_unknown_vertex_and_empty(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)

        with pytest.raises(ValueError, match="no 'vertices'"):
            ComputationGraphConfiguration.from_reference_json("{}")
        with pytest.raises(ValueError, match="unknown reference graph"):
            ComputationGraphConfiguration.from_reference_json(json.dumps({
                "vertices": {"x": {"FrobnicateVertex": {}}},
                "vertexInputs": {"x": ["in"]},
                "networkInputs": ["in"], "networkOutputs": ["x"],
            }))


class TestReferenceYamlLoader:
    """Reference toYaml() compat for both conf classes
    (NeuralNetConfiguration.java:214-239,
    ComputationGraphConfiguration.java:86-96). Documents are hand-built to
    Jackson/SnakeYAML block conventions: '---' marker, double-quoted
    strings, camelCase fields, wrapper-object tags as nested mappings."""

    MLN_YAML = '\n'.join([
        '---',
        'backprop: true',
        'pretrain: false',
        'backpropType: "TruncatedBPTT"',
        'tbpttFwdLength: 8',
        'tbpttBackLength: 8',
        'confs:',
        '- layer:',
        '    gravesLSTM:',
        '      nIn: 10',
        '      nOut: 16',
        '      activationFunction: "tanh"',
        '      updater: "ADAM"',
        '      learningRate: 0.02',
        '  seed: 7',
        '  numIterations: 1',
        '  optimizationAlgo: "STOCHASTIC_GRADIENT_DESCENT"',
        '- layer:',
        '    rnnoutput:',
        '      nIn: 16',
        '      nOut: 10',
        '      activationFunction: "softmax"',
        '      lossFunction: "MCXENT"',
        '      updater: "ADAM"',
        '      learningRate: 0.02',
        '  seed: 7',
        '  numIterations: 1',
    ]) + '\n'

    def test_mln_reference_yaml(self):
        conf = MultiLayerConfiguration.from_reference_yaml(self.MLN_YAML)
        assert conf.backprop_type == BackpropType.TRUNCATED_BPTT
        assert conf.tbptt_fwd_length == 8
        kinds = [type(l).__name__ for l in conf.layers]
        assert kinds == ["GravesLSTM", "RnnOutputLayer"]
        assert conf.layers[0].n_out == 16
        assert conf.global_conf.seed == 7
        # equivalent JSON document loads to an equal configuration
        as_json = json.dumps({
            "backprop": True, "pretrain": False,
            "backpropType": "TruncatedBPTT",
            "tbpttFwdLength": 8, "tbpttBackLength": 8,
            "confs": [
                {"layer": {"gravesLSTM": {
                    "nIn": 10, "nOut": 16, "activationFunction": "tanh",
                    "updater": "ADAM", "learningRate": 0.02}},
                 "seed": 7, "numIterations": 1,
                 "optimizationAlgo": "STOCHASTIC_GRADIENT_DESCENT"},
                {"layer": {"rnnoutput": {
                    "nIn": 16, "nOut": 10, "activationFunction": "softmax",
                    "lossFunction": "MCXENT", "updater": "ADAM",
                    "learningRate": 0.02}},
                 "seed": 7, "numIterations": 1},
            ],
        })
        assert conf == MultiLayerConfiguration.from_reference_json(as_json)

    GRAPH_YAML = '\n'.join([
        '---',
        'vertices:',
        '  d1:',
        '    LayerVertex:',
        '      layerConf:',
        '        layer:',
        '          dense:',
        '            nIn: 4',
        '            nOut: 3',
        '            activationFunction: "relu"',
        '            learningRate: 0.05',
        '        seed: 5',
        '  sub:',
        '    SubsetVertex:',
        '      from: 0',
        '      to: 1',
        '  out:',
        '    LayerVertex:',
        '      layerConf:',
        '        layer:',
        '          output:',
        '            nIn: 2',
        '            nOut: 2',
        '            lossFunction: "MCXENT"',
        '            learningRate: 0.05',
        '        seed: 5',
        'vertexInputs:',
        '  d1:',
        '  - "in"',
        '  sub:',
        '  - "d1"',
        '  out:',
        '  - "sub"',
        'networkInputs:',
        '- "in"',
        'networkOutputs:',
        '- "out"',
        'backprop: true',
        'pretrain: false',
    ]) + '\n'

    def test_graph_reference_yaml(self):
        from deeplearning4j_tpu.nn.conf import graph as G
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = ComputationGraphConfiguration.from_reference_yaml(
            self.GRAPH_YAML)
        assert conf.inputs == ["in"] and conf.outputs == ["out"]
        sub = conf.vertices["sub"]
        assert isinstance(sub, G.SubsetVertex)
        assert (sub.from_index, sub.to_index) == (0, 1)
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(2)
        x = rng.random((6, 4), np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
        net.fit([x], [y])
        assert np.isfinite(net.score_value)


class TestReferenceExport:
    """to_reference_json — the EXPORT half of the ecosystem contract.
    Semantic round-trip: a config exported to the reference format and
    re-imported must build a network with IDENTICAL outputs (same seed →
    same init), which is stronger than structural equality (the formats
    normalize learning-rate placement differently)."""

    def _assert_semantic_roundtrip(self, conf, x):
        back = MultiLayerConfiguration.from_reference_json(
            conf.to_reference_json())
        n1 = MultiLayerNetwork(conf).init()
        n2 = MultiLayerNetwork(back).init()
        o1 = np.asarray(n1.output(x))
        o2 = np.asarray(n2.output(x))
        np.testing.assert_allclose(o1, o2, rtol=1e-6, atol=1e-7)
        # and one training step keeps them in lockstep (optimizer
        # hyperparams survived the trip)
        from deeplearning4j_tpu.datasets.dataset import DataSet

        y = np.eye(o1.shape[-1], dtype=np.float32)[
            np.zeros(x.shape[0], np.int64)]
        n1.fit(DataSet(x, y))
        n2.fit(DataSet(x, y))
        np.testing.assert_allclose(np.asarray(n1.output(x)),
                                   np.asarray(n2.output(x)),
                                   rtol=1e-5, atol=1e-6)

    def test_mlp_round_trip(self):
        conf = (
            NeuralNetConfiguration.Builder().seed(11).learning_rate(0.05)
            .updater(Updater.ADAM).list()
            .layer(0, L.DenseLayer(n_in=6, n_out=8, activation="relu",
                                   l2=1e-4, dropout=0.0))
            .layer(1, L.OutputLayer(n_in=8, n_out=3,
                                    loss_function=LossFunction.MCXENT))
            .build()
        )
        x = np.random.default_rng(0).random((4, 6), np.float32)
        self._assert_semantic_roundtrip(conf, x)

    def test_conv_with_preprocessor_round_trip(self):
        conf = (
            NeuralNetConfiguration.Builder().seed(3).learning_rate(0.02)
            .updater(Updater.RMSPROP).list()
            .layer(0, L.ConvolutionLayer(n_in=1, n_out=4,
                                         kernel_size=(3, 3),
                                         stride=(1, 1), padding=(0, 0),
                                         activation="relu",
                                         weight_init=WeightInit.XAVIER))
            .layer(1, L.DenseLayer(n_in=4 * 6 * 6, n_out=10,
                                   activation="tanh"))
            .layer(2, L.OutputLayer(n_in=10, n_out=2,
                                    loss_function=LossFunction.MCXENT))
            .input_pre_processor(1, CnnToFeedForwardPreProcessor(
                height=6, width=6, channels=4))
            .build()
        )
        doc = json.loads(conf.to_reference_json())
        assert "cnnToFeedForward" in doc["inputPreProcessors"]["1"]
        assert doc["confs"][0]["layer"]["convolution"]["kernelSize"] == [3, 3]
        x = np.random.default_rng(1).random((2, 8, 8, 1), np.float32)
        self._assert_semantic_roundtrip(conf, x)

    def test_fuzz_random_dense_stacks(self):
        """Randomized configs: export → import → identical outputs."""
        rng = np.random.default_rng(7)
        acts = ["relu", "tanh", "sigmoid", "leakyrelu"]
        upds = [Updater.SGD, Updater.ADAM, Updater.RMSPROP,
                Updater.ADAGRAD, Updater.NESTEROVS]
        for trial in range(8):
            depth = int(rng.integers(1, 4))
            widths = [int(rng.integers(3, 9)) for _ in range(depth + 1)]
            b = (NeuralNetConfiguration.Builder()
                 .seed(int(rng.integers(0, 2 ** 31 - 1)))
                 .learning_rate(float(rng.choice([0.5, 0.05, 0.01])))
                 .updater(upds[trial % len(upds)])
                 .list())
            n_in = 5
            for i, w in enumerate(widths[:-1]):
                b.layer(i, L.DenseLayer(
                    n_in=n_in, n_out=w,
                    activation=str(rng.choice(acts)),
                    l1=float(rng.choice([0.0, 1e-5])),
                    l2=float(rng.choice([0.0, 1e-4]))))
                n_in = w
            b.layer(depth, L.OutputLayer(
                n_in=n_in, n_out=widths[-1],
                loss_function=LossFunction.MCXENT))
            conf = b.build()
            x = rng.random((3, 5), np.float32)
            self._assert_semantic_roundtrip(conf, x)

    def test_graph_export_round_trip(self):
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        g = (
            NeuralNetConfiguration.Builder().seed(5).learning_rate(0.05)
            .updater(Updater.ADAM)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", L.DenseLayer(n_in=4, n_out=6,
                                          activation="relu"), "in")
            .add_layer("d2", L.DenseLayer(n_in=4, n_out=6,
                                          activation="tanh"), "in")
            .add_vertex("merge", __import__(
                "deeplearning4j_tpu.nn.conf.graph",
                fromlist=["MergeVertex"]).MergeVertex(), "d1", "d2")
            .add_layer("out", L.OutputLayer(
                n_in=12, n_out=2,
                loss_function=LossFunction.MCXENT), "merge")
            .set_outputs("out")
        )
        conf = g.build()
        back = ComputationGraphConfiguration.from_reference_json(
            conf.to_reference_json())
        assert back.inputs == conf.inputs
        assert back.outputs == conf.outputs
        assert set(back.layers) == set(conf.layers)
        assert back.vertex_inputs == conf.vertex_inputs
        x = np.random.default_rng(2).random((3, 4), np.float32)
        o1 = ComputationGraph(conf).init().output(x)
        o2 = ComputationGraph(back).init().output(x)
        np.testing.assert_allclose(np.asarray(o1[0]), np.asarray(o2[0]),
                                   rtol=1e-6, atol=1e-7)

    def test_lr_schedule_round_trips(self):
        """learningRateSchedule is a serialized per-layer reference field
        (Layer.java:72): it must survive export → import into the native
        global schedule, not silently vanish."""
        conf = (
            NeuralNetConfiguration.Builder().seed(1).learning_rate(0.1)
            .learning_rate_schedule({5: 0.01, 20: 0.001})
            .list()
            .layer(0, L.DenseLayer(n_in=4, n_out=3, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=3, n_out=2,
                                    loss_function=LossFunction.MCXENT))
            .build()
        )
        doc = json.loads(conf.to_reference_json())
        assert doc["confs"][0]["layer"]["dense"][
            "learningRateSchedule"] == {"5": 0.01, "20": 0.001}
        back = MultiLayerConfiguration.from_reference_json(
            conf.to_reference_json())
        assert back.global_conf.lr_schedule == {5: 0.01, 20: 0.001}

    def test_inexpressible_fields_raise(self):
        """Native-only semantics-bearing settings must fail fast at
        export, not silently re-import as a different network."""
        base = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.01))
        conv_conf = (base.list()
                     .layer(0, L.ConvolutionLayer(
                         n_in=1, n_out=2, kernel_size=(3, 3),
                         stride=(1, 1), convolution_mode="same"))
                     .layer(1, L.OutputLayer(
                         n_in=8, n_out=2,
                         loss_function=LossFunction.MCXENT))
                     .build())
        with pytest.raises(ValueError, match="convolution_mode"):
            conv_conf.to_reference_json()
        bf16 = (NeuralNetConfiguration.Builder().seed(0)
                .learning_rate(0.01).dtype_policy("bf16").list()
                .layer(0, L.OutputLayer(n_in=4, n_out=2,
                                        loss_function=LossFunction.MCXENT))
                .build())
        with pytest.raises(ValueError, match="dtype_policy"):
            bf16.to_reference_json()

    def test_reference_yaml_export_round_trips(self):
        conf = (NeuralNetConfiguration.Builder().seed(2)
                .learning_rate(0.05).updater(Updater.ADAM).list()
                .layer(0, L.DenseLayer(n_in=4, n_out=3,
                                       activation="tanh"))
                .layer(1, L.OutputLayer(n_in=3, n_out=2,
                                        loss_function=LossFunction.MCXENT))
                .build())
        back = MultiLayerConfiguration.from_reference_yaml(
            conf.to_reference_yaml())
        x = np.random.default_rng(4).random((3, 4), np.float32)
        o1 = np.asarray(MultiLayerNetwork(conf).init().output(x))
        o2 = np.asarray(MultiLayerNetwork(back).init().output(x))
        np.testing.assert_allclose(o1, o2, rtol=1e-6, atol=1e-7)
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration)

        g = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
             .graph_builder()
             .add_inputs("in")
             .add_layer("out", L.OutputLayer(
                 n_in=4, n_out=2, loss_function=LossFunction.MCXENT), "in")
             .set_outputs("out"))
        gc = g.build()
        gback = ComputationGraphConfiguration.from_reference_yaml(
            gc.to_reference_yaml())
        assert set(gback.layers) == {"out"}
        assert gback.inputs == ["in"]

    def test_explicit_zero_hyperparams_raise(self):
        """The reference format writes 0.0 for UNSET updater
        hyperparameters (why the importer's _ZERO_MEANS_UNSET drops
        zeros) — an explicit 0.0 would re-import as the default, so
        export must refuse it."""
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .learning_rate(0.01).list()
                .layer(0, L.DenseLayer(n_in=4, n_out=3, momentum=0.0,
                                       updater=Updater.NESTEROVS))
                .layer(1, L.OutputLayer(n_in=3, n_out=2,
                                        loss_function=LossFunction.MCXENT))
                .build())
        with pytest.raises(ValueError, match="momentum=0.0"):
            conf.to_reference_json()
        frozen = (NeuralNetConfiguration.Builder().seed(0)
                  .learning_rate(0.0).list()
                  .layer(0, L.OutputLayer(n_in=4, n_out=2,
                                          loss_function=LossFunction.MCXENT))
                  .build())
        with pytest.raises(ValueError, match="learning_rate=0.0"):
            frozen.to_reference_json()

    def test_elementwise_average_raises(self):
        from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex

        g = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.01)
            .graph_builder()
            .add_inputs("a", "b")
            .add_vertex("avg", ElementWiseVertex(op="Average"), "a", "b")
            .add_layer("out", L.OutputLayer(
                n_in=4, n_out=2, loss_function=LossFunction.MCXENT), "avg")
            .set_outputs("out")
        )
        with pytest.raises(ValueError, match="Add/Subtract/Product"):
            g.build().to_reference_json()

    def test_inexpressible_vertex_raises(self):
        from deeplearning4j_tpu.nn.conf.graph import ScaleVertex

        g = (
            NeuralNetConfiguration.Builder().seed(0).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_vertex("s", ScaleVertex(scale=2.0), "in")
            .add_layer("out", L.OutputLayer(
                n_in=4, n_out=2, loss_function=LossFunction.MCXENT), "s")
            .set_outputs("out")
        )
        with pytest.raises(ValueError, match="cannot express"):
            g.build().to_reference_json()


class TestReferenceJsonFullLayerMatrix:
    """Every Jackson wrapper tag in Layer.java:44-59 translates."""

    def test_all_layer_tags_translate(self):
        docs = {
            "autoEncoder": {"nIn": 8, "nOut": 4},
            "convolution": {"nIn": 1, "nOut": 4, "kernelSize": [3, 3],
                            "stride": [1, 1], "padding": [0, 0]},
            "imageLSTM": {"nIn": 8, "nOut": 6},
            "gravesLSTM": {"nIn": 8, "nOut": 6},
            "gravesBidirectionalLSTM": {"nIn": 8, "nOut": 6},
            "gru": {"nIn": 8, "nOut": 6},
            "output": {"nIn": 8, "nOut": 3, "lossFunction": "MCXENT"},
            "rnnoutput": {"nIn": 8, "nOut": 3, "lossFunction": "MCXENT"},
            "RBM": {"nIn": 8, "nOut": 4, "hiddenUnit": "BINARY",
                    "visibleUnit": "BINARY", "k": 1},
            "dense": {"nIn": 8, "nOut": 4},
            "recursiveAutoEncoder": {"nIn": 8, "nOut": 8},
            "subsampling": {"poolingType": "AVG", "kernelSize": [2, 2],
                            "stride": [2, 2], "padding": [0, 0]},
            "batchNormalization": {"nIn": 8, "nOut": 8, "decay": 0.9,
                                   "eps": 1e-5},
            "localResponseNormalization": {"n": 5.0, "alpha": 1e-4,
                                           "beta": 0.75},
            "embedding": {"nIn": 20, "nOut": 8},
            "activation": {"activationFunction": "relu"},
        }
        from deeplearning4j_tpu.nn.conf.compat import _convert_layer
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.enums import HiddenUnit, PoolingType

        expected = {
            "autoEncoder": L.AutoEncoder, "convolution": L.ConvolutionLayer,
            "imageLSTM": L.ImageLSTM, "gravesLSTM": L.GravesLSTM,
            "gravesBidirectionalLSTM": L.GravesBidirectionalLSTM,
            "gru": L.GRU, "output": L.OutputLayer,
            "rnnoutput": L.RnnOutputLayer, "RBM": L.RBM,
            "dense": L.DenseLayer,
            "recursiveAutoEncoder": L.RecursiveAutoEncoder,
            "subsampling": L.SubsamplingLayer,
            "batchNormalization": L.BatchNormalization,
            "localResponseNormalization": L.LocalResponseNormalization,
            "embedding": L.EmbeddingLayer, "activation": L.ActivationLayer,
        }
        for tag, fields in docs.items():
            layer = _convert_layer({tag: fields})
            assert type(layer) is expected[tag], tag
        rbm = _convert_layer({"RBM": docs["RBM"]})
        assert rbm.hidden_unit == HiddenUnit.BINARY
        sub = _convert_layer({"subsampling": docs["subsampling"]})
        assert sub.pooling_type == PoolingType.AVG
