"""Cluster runtime tests: state tracker job lifecycle, heartbeat eviction,
fault-tolerant checkpoint/resume (the reference's MasterActor heartbeat +
ModelSavingActor semantics, SURVEY §3.4/§5, tested in-process the way the
reference uses BaseTestDistributed) — plus chaos cases proving end-to-end
recovery under injected faults (corrupt newest checkpoint → fallback to
older; hung worker → eviction → requeue → run completes)."""

import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ClusterConfig,
    FaultTolerantTrainer,
    FileStateTracker,
    HeartbeatMonitor,
    InMemoryStateTracker,
    initialize_distributed,
)
from deeplearning4j_tpu.resilience import (
    RetryPolicy,
    fail_times,
    faults,
    inject,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def toy(n=64, d=6, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(c)[rng.integers(0, c, n)].astype(np.float32)
    return DataSet(x, y)


def make_net(seed=1):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="relu"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


@pytest.fixture(params=["memory", "file"])
def tracker(request, tmp_path):
    if request.param == "memory":
        return InMemoryStateTracker()
    return FileStateTracker(str(tmp_path / "tracker"))


class TestStateTracker:
    def test_job_lifecycle(self, tracker):
        jid = tracker.add_job({"batch": 0})
        assert tracker.jobs(status="pending")[0].job_id == jid
        j = tracker.claim_job("w1")
        assert j.job_id == jid and j.worker_id == "w1" and j.attempts == 1
        assert tracker.claim_job("w2") is None  # nothing left
        tracker.complete_job(jid, result={"loss": 0.5})
        done = tracker.jobs(status="done")
        assert len(done) == 1 and done[0].result == {"loss": 0.5}

    def test_fifo_claim_order(self, tracker):
        ids = [tracker.add_job(i) for i in range(3)]
        claimed = [tracker.claim_job("w").job_id for _ in range(3)]
        assert claimed == ids

    def test_fail_requeues(self, tracker):
        jid = tracker.add_job("x")
        tracker.claim_job("w1")
        tracker.fail_job(jid, requeue=True)
        j = tracker.claim_job("w2")
        assert j.job_id == jid and j.attempts == 2

    def test_fail_terminal(self, tracker):
        jid = tracker.add_job("x")
        tracker.claim_job("w1")
        tracker.fail_job(jid, requeue=False)
        assert tracker.claim_job("w2") is None
        assert tracker.jobs(status="failed")[0].job_id == jid

    def test_heartbeat_and_eviction_requeues_jobs(self, tracker):
        jid = tracker.add_job("x")
        tracker.heartbeat("w1")
        tracker.claim_job("w1")
        assert "w1" in tracker.workers()
        assert tracker.evict_stale(timeout_s=60.0) == []  # fresh
        time.sleep(0.05)
        assert tracker.evict_stale(timeout_s=0.01) == ["w1"]
        assert tracker.workers() == []
        # the dead worker's claimed job went back to pending
        j = tracker.claim_job("w2")
        assert j.job_id == jid and j.attempts == 2

    def test_meta_roundtrip(self, tracker):
        tracker.put_meta("conf", {"lr": 0.1})
        assert tracker.get_meta("conf") == {"lr": 0.1}
        assert tracker.get_meta("missing", 42) == 42


class TestHeartbeatMonitor:
    def test_background_beats(self):
        tracker = InMemoryStateTracker()
        with HeartbeatMonitor(tracker, "w1", interval_s=0.02):
            time.sleep(0.1)
            t1 = tracker.last_heartbeat("w1")
            time.sleep(0.1)
            t2 = tracker.last_heartbeat("w1")
        assert t1 is not None and t2 > t1
        final = tracker.last_heartbeat("w1")
        time.sleep(0.1)
        assert tracker.last_heartbeat("w1") == final  # stopped


class TestInitializeDistributed:
    def test_single_process_noop(self):
        assert initialize_distributed(ClusterConfig()) is False
        assert initialize_distributed(
            ClusterConfig(coordinator_address=None, num_processes=4)) is False


class TestFaultTolerantTrainer:
    def test_checkpoints_written_and_pruned(self, tmp_path):
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "ck"),
                                  checkpoint_every=2, keep=2)
        ds = toy()
        for _ in range(7):
            net.fit(ds)
            if net.iteration_count % ft.every == 0:
                ft.save()
        assert len(ft.checkpoints()) == 2  # pruned to keep=2
        assert ft.latest_checkpoint().endswith(
            f"ckpt-{net.iteration_count - net.iteration_count % 2:012d}.zip"
            if net.iteration_count % 2 else
            f"ckpt-{net.iteration_count:012d}.zip")

    def test_crash_resume_continues_identically(self, tmp_path):
        ds = toy()
        # uninterrupted run: 6 iterations
        ref = make_net(seed=3)
        for _ in range(6):
            ref.fit(ds)

        # interrupted run: 4 iterations, checkpoint, "crash", resume, 2 more
        net1 = make_net(seed=3)
        ft1 = FaultTolerantTrainer(net1, str(tmp_path / "ck"),
                                   checkpoint_every=4)
        for _ in range(4):
            net1.fit(ds)
        ft1.save()
        del net1  # crash

        net2 = make_net(seed=99)  # fresh process, different init
        ft2 = FaultTolerantTrainer(net2, str(tmp_path / "ck"))
        assert ft2.resume() is True
        assert net2.iteration_count == 4
        for _ in range(2):
            net2.fit(ds)
        np.testing.assert_allclose(
            ref.get_flat_params(), net2.get_flat_params(),
            rtol=1e-5, atol=1e-6)

    def test_resume_without_checkpoint(self, tmp_path):
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "empty"))
        assert ft.resume() is False

    def test_fit_loop_heartbeats_and_saves(self, tmp_path):
        tracker = InMemoryStateTracker()
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "ck"),
                                  checkpoint_every=2, tracker=tracker,
                                  worker_id="w-7")
        ft.fit(toy(), num_epochs=1)
        assert tracker.last_heartbeat("w-7") is not None
        assert tracker.get_meta("latest_checkpoint") == ft.latest_checkpoint()
        assert os.path.exists(ft.latest_checkpoint())


class TestReviewRegressions:
    def test_heartbeat_monitor_restart(self):
        tracker = InMemoryStateTracker()
        m = HeartbeatMonitor(tracker, "w1", interval_s=0.02)
        m.start(); time.sleep(0.05); m.stop()
        m.start()
        time.sleep(0.08)
        t1 = tracker.last_heartbeat("w1")
        time.sleep(0.08)
        t2 = tracker.last_heartbeat("w1")
        m.stop()
        assert t2 > t1  # periodic beats resumed after restart

    def test_stale_lock_broken_and_job_claimable(self, tmp_path):
        tr = FileStateTracker(str(tmp_path / "t"))
        jid = tr.add_job("x")
        # simulate a crashed claimer: stale lock file left behind
        lock = os.path.join(tr.root, "locks", "claim-" + jid)
        open(lock, "w").close()
        old = time.time() - 120
        os.utime(lock, (old, old))
        j = tr.claim_job("w2")
        assert j is not None and j.job_id == jid


# ---------------------------------------------------------------------------
# chaos: verified checkpoint recovery
# ---------------------------------------------------------------------------


def _two_checkpoints(tmp_path, seed=3):
    """Train 4 iters → save, 4 more → save. Returns (ft, older, newer)."""
    ds = toy()
    net = make_net(seed=seed)
    ft = FaultTolerantTrainer(net, str(tmp_path / "ck"), checkpoint_every=4)
    for _ in range(4):
        net.fit(ds)
    ft.save()
    for _ in range(4):
        net.fit(ds)
    ft.save()
    cks = ft.checkpoints()
    assert len(cks) == 2
    return ft, cks[0], cks[1]


@pytest.mark.chaos
class TestVerifiedRecovery:
    def test_manifest_written_and_pruned_with_checkpoint(self, tmp_path):
        ds = toy()
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "ck"),
                                  checkpoint_every=1, keep=2)
        for _ in range(4):
            net.fit(ds)
            ft.save()
        cks = ft.checkpoints()
        assert len(cks) == 2
        for ck in cks:
            assert os.path.exists(ck + ".sha256")
            assert ft.verify_checkpoint(ck) == "ok"
        # pruned checkpoints took their sidecars with them
        sidecars = [f for f in os.listdir(ft.dir) if f.endswith(".sha256")]
        assert len(sidecars) == 2

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        ft, older, newer = _two_checkpoints(tmp_path)
        with open(newer, "wb") as f:
            f.write(b"this is not a checkpoint")
        assert ft.verify_checkpoint(newer) == "corrupt"

        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        assert net2.iteration_count == 4  # the older checkpoint's state

    def test_truncated_newest_falls_back(self, tmp_path):
        ft, older, newer = _two_checkpoints(tmp_path)
        size = os.path.getsize(newer)
        with open(newer, "r+b") as f:
            f.truncate(size // 2)  # partial write / power cut
        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        assert net2.iteration_count == 4

    def test_all_corrupt_raises_instead_of_fresh_start(self, tmp_path):
        ft, older, newer = _two_checkpoints(tmp_path)
        for ck in (older, newer):
            with open(ck, "wb") as f:
                f.write(b"garbage")
        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        with pytest.raises(RuntimeError, match="corrupt"):
            ft2.resume()

    def test_legacy_checkpoint_without_manifest_still_loads(self, tmp_path):
        ft, older, newer = _two_checkpoints(tmp_path)
        os.unlink(newer + ".sha256")  # pre-manifest writer
        assert ft.verify_checkpoint(newer) == "unverified"
        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        assert net2.iteration_count == 8  # unverified but loadable: used

    def test_unverified_corrupt_still_falls_back(self, tmp_path):
        # no sidecar AND corrupt: the zip-load failure must fall through
        ft, older, newer = _two_checkpoints(tmp_path)
        os.unlink(newer + ".sha256")
        with open(newer, "wb") as f:
            f.write(b"garbage")
        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        assert net2.iteration_count == 4

    def test_resumed_fallback_continues_training(self, tmp_path):
        ds = toy()
        ft, older, newer = _two_checkpoints(tmp_path)
        with open(newer, "wb") as f:
            f.write(b"junk")
        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        s0 = net2.score(ds)
        for _ in range(4):
            net2.fit(ds)
        assert net2.score(ds) < s0  # recovered state trains on

    def test_save_crash_injection_leaves_state_consistent(self, tmp_path):
        from deeplearning4j_tpu.resilience import FaultInjected, fail_nth

        ft, older, newer = _two_checkpoints(tmp_path)
        net = ft.network
        net.fit(toy())
        with inject("checkpoint.save", fail_nth(1)):
            with pytest.raises(FaultInjected):
                ft.save()
        # the failed save left no partial archive: both old checkpoints
        # still verify and resume still works
        assert ft.checkpoints() == [older, newer]
        assert ft.verify_checkpoint(newer) == "ok"

    def test_torn_write_falls_back_to_previous_intact(self, tmp_path):
        """A save that dies MID-WRITE (power cut after some bytes
        landed, before the manifest): the torn archive sits at the
        final path with no sidecar, and resume must fall back to the
        previous intact candidate rather than load garbage or
        fresh-start."""
        from deeplearning4j_tpu.resilience import FaultInjected

        ft, older, newer = _two_checkpoints(tmp_path)
        net = ft.network
        net.fit(toy())

        def torn_write(site):
            # model the torn write itself: partial bytes land at the
            # final path, then the crash — no manifest is ever written
            with open(ft._ckpt_path(net.iteration_count), "wb") as f:
                f.write(b"PK\x03\x04 torn mid-write")
            raise FaultInjected(f"injected torn write at {site}")

        with inject("checkpoint.save", torn_write):
            with pytest.raises(FaultInjected):
                ft.save()
        torn = ft._ckpt_path(net.iteration_count)
        assert os.path.exists(torn)
        assert not os.path.exists(torn + ".sha256")

        net2 = make_net(seed=99)
        ft2 = FaultTolerantTrainer(net2, ft.dir)
        assert ft2.resume() is True
        assert net2.iteration_count == 8  # newest INTACT candidate


# ---------------------------------------------------------------------------
# chaos: initialize_distributed retry path
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestInitializeDistributedRetry:
    CFG = ClusterConfig(coordinator_address="127.0.0.1:1", num_processes=2,
                        process_id=0)

    def test_injected_faults_exhaust_deterministically(self):
        sleeps = []
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.01, seed=5,
                             sleep=sleeps.append)
        # faults fire before jax.distributed is ever touched
        with inject("distributed.init", fail_times(10)):
            with pytest.raises(RuntimeError, match="after 3 attempts"):
                initialize_distributed(self.CFG, policy=policy)
        assert len(sleeps) == 2  # attempts-1 backoffs, jittered+recorded
        assert all(0.0 <= s <= 0.04 for s in sleeps)

    def test_transient_init_then_success(self, monkeypatch):
        import jax

        calls = {"n": 0}

        def flaky_init(**kw):
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("coordinator not up yet")

        monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
        policy = RetryPolicy(max_attempts=5, base_delay_s=0.001,
                             sleep=lambda s: None)
        assert initialize_distributed(self.CFG, policy=policy) is True
        assert calls["n"] == 3

    def test_legacy_knobs_seed_default_policy(self, monkeypatch):
        import jax

        def always_down(**kw):
            raise RuntimeError("down")

        monkeypatch.setattr(jax.distributed, "initialize", always_down)
        monkeypatch.setattr(time, "sleep", lambda s: None)
        with pytest.raises(RuntimeError, match="after 2 attempts"):
            initialize_distributed(self.CFG, retries=2, retry_delay_s=0.001)


class TestHeartbeatMonitorHardening:
    def test_stop_idempotent(self):
        tracker = InMemoryStateTracker()
        m = HeartbeatMonitor(tracker, "w1", interval_s=0.02)
        m.start()
        m.stop()
        m.stop()  # second stop is a no-op, not an error
        assert tracker.last_heartbeat("w1") is not None

    def test_rapid_stop_start_cycles_beat_cleanly(self):
        tracker = InMemoryStateTracker()
        m = HeartbeatMonitor(tracker, "w1", interval_s=0.01)
        for _ in range(5):
            m.start()
            m.stop()
        m.start()
        time.sleep(0.06)
        t1 = tracker.last_heartbeat("w1")
        time.sleep(0.06)
        t2 = tracker.last_heartbeat("w1")
        m.stop()
        assert t2 > t1  # exactly one live thread, still beating

    def test_start_twice_single_thread(self):
        tracker = InMemoryStateTracker()
        m = HeartbeatMonitor(tracker, "w1", interval_s=0.02)
        assert m.start() is m.start()
        thread = m._thread
        m.start()
        assert m._thread is thread  # no second thread spawned
        m.stop()


# ---------------------------------------------------------------------------
# chaos: end-to-end — kill a worker mid-job, corrupt the newest checkpoint
# ---------------------------------------------------------------------------


class _DieFirstPerformer:
    """Simulates a worker PROCESS dying mid-job: the first perform()
    across the pool stops that worker's heartbeat monitor (a dead process
    takes its monitor thread with it) and wedges forever. Later calls on
    other workers run normally. Workers heartbeat from a background
    monitor, so a merely-SLOW job keeps beating and is never evicted —
    only this death shape goes silent."""

    _lock = threading.Lock()
    _dead = False

    def __init__(self, worker_id, trainer_ref):
        self.worker_id = worker_id
        self.trainer_ref = trainer_ref
        self.received = []

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._dead = False

    @classmethod
    def factory(cls, trainer_ref):
        made = []

        def make():
            p = cls(f"worker-{len(made)}", trainer_ref)
            made.append(p)
            return p

        return make

    def _die_if_first(self) -> bool:
        cls = type(self)
        with cls._lock:
            should_die = not cls._dead
            cls._dead = True
        if should_die:
            self.trainer_ref["trainer"].monitors[self.worker_id].stop()
            threading.Event().wait()  # never set: wedged forever
        return should_die

    def perform(self, payload):
        self._die_if_first()
        return np.asarray(payload["value"], np.float32)

    def update(self, params):
        self.received.append(np.asarray(params))


@pytest.mark.chaos
class TestChaosEndToEnd:
    def test_worker_crash_eviction_requeue_completes(self, tmp_path):
        from deeplearning4j_tpu.parallel import (
            DistributedTrainer,
            IterativeReduceWorkRouter,
        )

        _DieFirstPerformer.reset()
        tracker = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tracker)
        for i in range(4):
            tracker.add_job({"value": [float(i + 1)]})
        ref = {}
        trainer = DistributedTrainer(
            tracker, router, _DieFirstPerformer.factory(ref),
            num_workers=2, poll_s=0.01, join_timeout_s=0.2,
            heartbeat_interval_s=0.05,
            eviction_timeout_s=0.3)  # MasterActor-style liveness eviction
        ref["trainer"] = trainer
        params = trainer.train(timeout_s=30.0)
        # exactly the dead worker was evicted (the survivor kept beating
        # from its background monitor) and its claimed job was requeued …
        assert len(set(trainer.evicted)) == 1
        # … and every job still completed (on the surviving worker)
        assert len(tracker.jobs(status="done")) == 4
        assert tracker.jobs(status="pending") == []
        assert params is not None

    def test_corrupt_checkpoint_and_worker_crash_full_recovery(
            self, tmp_path):
        """The acceptance scenario, end to end: the newest checkpoint is
        corrupted AND one worker dies mid-job — resume() restores the
        next-older verified checkpoint, the master evicts the dead worker
        and requeues its job, and distributed training completes."""
        from deeplearning4j_tpu.parallel import (
            DistributedTrainer,
            IterativeReduceWorkRouter,
            NetworkWorkPerformer,
        )

        # -- phase 1: crash-restart with a corrupted newest checkpoint --
        ft, older, newer = _two_checkpoints(tmp_path, seed=3)
        with open(newer, "wb") as f:
            f.write(b"flipped bits")
        net = make_net(seed=99)  # relaunched process, fresh init
        ft2 = FaultTolerantTrainer(net, ft.dir)
        assert ft2.resume() is True
        assert net.iteration_count == 4  # next-older verified checkpoint

        # -- phase 2: finish training distributed, surviving one death --
        ref = {}

        class DieFirstNetworkPerformer(_DieFirstPerformer,
                                       NetworkWorkPerformer):
            def __init__(self, worker_id, trainer_ref, conf_json):
                NetworkWorkPerformer.__init__(self, conf_json)
                self.worker_id = worker_id
                self.trainer_ref = trainer_ref

            def perform(self, payload):
                self._die_if_first()
                return NetworkWorkPerformer.perform(self, payload)

            def update(self, params):
                NetworkWorkPerformer.update(self, params)

        DieFirstNetworkPerformer.reset()
        made = []

        def factory():
            p = DieFirstNetworkPerformer(f"worker-{len(made)}", ref,
                                         conf_json)
            made.append(p)
            return p

        conf_json = net.conf.to_json()
        tracker = InMemoryStateTracker()
        router = IterativeReduceWorkRouter(tracker)
        ds = toy()
        for start in range(0, 48, 16):
            tracker.add_job({
                "features": np.asarray(
                    ds.features[start:start + 16]).tolist(),
                "labels": np.asarray(ds.labels[start:start + 16]).tolist(),
            })
        trainer = DistributedTrainer(
            tracker, router, factory,
            num_workers=2, poll_s=0.01, join_timeout_s=0.2,
            heartbeat_interval_s=0.05, eviction_timeout_s=0.4)
        ref["trainer"] = trainer
        params = trainer.train(timeout_s=60.0)
        assert trainer.evicted  # the dead worker was noticed …
        assert len(tracker.jobs(status="done")) == 3  # … and its job ran
        assert params is not None and np.all(np.isfinite(params))
        assert params.shape == net.get_flat_params().shape
