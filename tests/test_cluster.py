"""Cluster runtime tests: state tracker job lifecycle, heartbeat eviction,
fault-tolerant checkpoint/resume (the reference's MasterActor heartbeat +
ModelSavingActor semantics, SURVEY §3.4/§5, tested in-process the way the
reference uses BaseTestDistributed)."""

import os
import time

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    ClusterConfig,
    FaultTolerantTrainer,
    FileStateTracker,
    HeartbeatMonitor,
    InMemoryStateTracker,
    initialize_distributed,
)


def toy(n=64, d=6, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(c)[rng.integers(0, c, n)].astype(np.float32)
    return DataSet(x, y)


def make_net(seed=1):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="relu"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


@pytest.fixture(params=["memory", "file"])
def tracker(request, tmp_path):
    if request.param == "memory":
        return InMemoryStateTracker()
    return FileStateTracker(str(tmp_path / "tracker"))


class TestStateTracker:
    def test_job_lifecycle(self, tracker):
        jid = tracker.add_job({"batch": 0})
        assert tracker.jobs(status="pending")[0].job_id == jid
        j = tracker.claim_job("w1")
        assert j.job_id == jid and j.worker_id == "w1" and j.attempts == 1
        assert tracker.claim_job("w2") is None  # nothing left
        tracker.complete_job(jid, result={"loss": 0.5})
        done = tracker.jobs(status="done")
        assert len(done) == 1 and done[0].result == {"loss": 0.5}

    def test_fifo_claim_order(self, tracker):
        ids = [tracker.add_job(i) for i in range(3)]
        claimed = [tracker.claim_job("w").job_id for _ in range(3)]
        assert claimed == ids

    def test_fail_requeues(self, tracker):
        jid = tracker.add_job("x")
        tracker.claim_job("w1")
        tracker.fail_job(jid, requeue=True)
        j = tracker.claim_job("w2")
        assert j.job_id == jid and j.attempts == 2

    def test_fail_terminal(self, tracker):
        jid = tracker.add_job("x")
        tracker.claim_job("w1")
        tracker.fail_job(jid, requeue=False)
        assert tracker.claim_job("w2") is None
        assert tracker.jobs(status="failed")[0].job_id == jid

    def test_heartbeat_and_eviction_requeues_jobs(self, tracker):
        jid = tracker.add_job("x")
        tracker.heartbeat("w1")
        tracker.claim_job("w1")
        assert "w1" in tracker.workers()
        assert tracker.evict_stale(timeout_s=60.0) == []  # fresh
        time.sleep(0.05)
        assert tracker.evict_stale(timeout_s=0.01) == ["w1"]
        assert tracker.workers() == []
        # the dead worker's claimed job went back to pending
        j = tracker.claim_job("w2")
        assert j.job_id == jid and j.attempts == 2

    def test_meta_roundtrip(self, tracker):
        tracker.put_meta("conf", {"lr": 0.1})
        assert tracker.get_meta("conf") == {"lr": 0.1}
        assert tracker.get_meta("missing", 42) == 42


class TestHeartbeatMonitor:
    def test_background_beats(self):
        tracker = InMemoryStateTracker()
        with HeartbeatMonitor(tracker, "w1", interval_s=0.02):
            time.sleep(0.1)
            t1 = tracker.last_heartbeat("w1")
            time.sleep(0.1)
            t2 = tracker.last_heartbeat("w1")
        assert t1 is not None and t2 > t1
        final = tracker.last_heartbeat("w1")
        time.sleep(0.1)
        assert tracker.last_heartbeat("w1") == final  # stopped


class TestInitializeDistributed:
    def test_single_process_noop(self):
        assert initialize_distributed(ClusterConfig()) is False
        assert initialize_distributed(
            ClusterConfig(coordinator_address=None, num_processes=4)) is False


class TestFaultTolerantTrainer:
    def test_checkpoints_written_and_pruned(self, tmp_path):
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "ck"),
                                  checkpoint_every=2, keep=2)
        ds = toy()
        for _ in range(7):
            net.fit(ds)
            if net.iteration_count % ft.every == 0:
                ft.save()
        assert len(ft.checkpoints()) == 2  # pruned to keep=2
        assert ft.latest_checkpoint().endswith(
            f"ckpt-{net.iteration_count - net.iteration_count % 2:012d}.zip"
            if net.iteration_count % 2 else
            f"ckpt-{net.iteration_count:012d}.zip")

    def test_crash_resume_continues_identically(self, tmp_path):
        ds = toy()
        # uninterrupted run: 6 iterations
        ref = make_net(seed=3)
        for _ in range(6):
            ref.fit(ds)

        # interrupted run: 4 iterations, checkpoint, "crash", resume, 2 more
        net1 = make_net(seed=3)
        ft1 = FaultTolerantTrainer(net1, str(tmp_path / "ck"),
                                   checkpoint_every=4)
        for _ in range(4):
            net1.fit(ds)
        ft1.save()
        del net1  # crash

        net2 = make_net(seed=99)  # fresh process, different init
        ft2 = FaultTolerantTrainer(net2, str(tmp_path / "ck"))
        assert ft2.resume() is True
        assert net2.iteration_count == 4
        for _ in range(2):
            net2.fit(ds)
        np.testing.assert_allclose(
            ref.get_flat_params(), net2.get_flat_params(),
            rtol=1e-5, atol=1e-6)

    def test_resume_without_checkpoint(self, tmp_path):
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "empty"))
        assert ft.resume() is False

    def test_fit_loop_heartbeats_and_saves(self, tmp_path):
        tracker = InMemoryStateTracker()
        net = make_net()
        ft = FaultTolerantTrainer(net, str(tmp_path / "ck"),
                                  checkpoint_every=2, tracker=tracker,
                                  worker_id="w-7")
        ft.fit(toy(), num_epochs=1)
        assert tracker.last_heartbeat("w-7") is not None
        assert tracker.get_meta("latest_checkpoint") == ft.latest_checkpoint()
        assert os.path.exists(ft.latest_checkpoint())


class TestReviewRegressions:
    def test_heartbeat_monitor_restart(self):
        tracker = InMemoryStateTracker()
        m = HeartbeatMonitor(tracker, "w1", interval_s=0.02)
        m.start(); time.sleep(0.05); m.stop()
        m.start()
        time.sleep(0.08)
        t1 = tracker.last_heartbeat("w1")
        time.sleep(0.08)
        t2 = tracker.last_heartbeat("w1")
        m.stop()
        assert t2 > t1  # periodic beats resumed after restart

    def test_stale_lock_broken_and_job_claimable(self, tmp_path):
        tr = FileStateTracker(str(tmp_path / "t"))
        jid = tr.add_job("x")
        # simulate a crashed claimer: stale lock file left behind
        lock = os.path.join(tr.root, "locks", "claim-" + jid)
        open(lock, "w").close()
        old = time.time() - 120
        os.utime(lock, (old, old))
        j = tr.claim_job("w2")
        assert j is not None and j.job_id == jid
