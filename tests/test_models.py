"""Model zoo + driver entry points: builders compile and take a train step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models import (
    char_lstm,
    lenet5,
    mnist_mlp,
    resnet18,
    transformer_lm,
)


class TestZoo:
    def test_mnist_mlp_step(self):
        net = mnist_mlp(hidden=32).init()
        rng = np.random.default_rng(0)
        x = rng.random((16, 784), np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        net.fit(x, y)
        assert np.isfinite(net.score_value)

    def test_lenet5_shapes_and_step(self):
        net = lenet5().init()
        rng = np.random.default_rng(0)
        x = rng.random((4, 28, 28, 1), np.float32)
        out = net.output(x)
        assert out.shape == (4, 10)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
        net.fit(x, y)
        assert np.isfinite(net.score_value)

    def test_char_lstm_tbptt_step(self):
        net = char_lstm(vocab_size=32, hidden=16, layers=1,
                        tbptt_length=8).init()
        rng = np.random.default_rng(0)
        t = 24
        idx = rng.integers(0, 32, (2, t))
        x = np.eye(32, dtype=np.float32)[idx]
        y = np.eye(32, dtype=np.float32)[np.roll(idx, -1, axis=1)]
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)
        # TBPTT split 24 into 3 windows of 8 → 3 iterations
        assert net.iteration_count == 3

    def test_resnet18_builds_and_steps(self):
        net = resnet18(num_classes=10).init()
        assert net.num_params() > 10_000_000  # ~11M for resnet-18
        rng = np.random.default_rng(0)
        x = rng.random((2, 32, 32, 3), np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 2)]
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score_value)
        out = net.output(x)[0]
        assert out.shape == (2, 10)

    def test_transformer_lm_learns_repetition(self):
        lm = transformer_lm(vocab_size=16, d_model=32, num_heads=4,
                            num_layers=2, max_len=32, lr=1e-2).init()
        rng = np.random.default_rng(0)
        # trivially learnable: constant-token sequences
        tokens = np.repeat(rng.integers(0, 16, (8, 1)), 32, axis=1)
        first = lm.fit_batch(tokens)
        for _ in range(30):
            last = lm.fit_batch(tokens)
        assert last < first * 0.2, (first, last)


class TestGlobalPooling:
    @pytest.mark.parametrize("pt", ["AVG", "MAX", "SUM"])
    def test_cnn_pooling_values(self, pt):
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.enums import PoolingType
        from deeplearning4j_tpu.nn.layers.base import get_layer_impl

        impl = get_layer_impl(L.GlobalPoolingLayer(pooling_type=PoolingType(pt)))
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4))
        y, _ = impl.forward({}, x, {})
        assert y.shape == (1, 4)
        expected = {
            "AVG": x.mean(axis=(1, 2)), "MAX": x.max(axis=(1, 2)),
            "SUM": x.sum(axis=(1, 2)),
        }[pt]
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected))

    def test_in_multilayer_network(self):
        """GlobalPoolingLayer must pass ListBuilder validation/inference."""
        from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.Builder().seed(0).list()
                .layer(0, L.GravesLSTM(n_out=6))
                .layer(1, L.GlobalPoolingLayer())
                .layer(2, L.OutputLayer(n_out=3))
                .set_input_type(InputType.recurrent(5))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(0).normal(size=(2, 7, 5)).astype(np.float32)
        assert net.output(x).shape == (2, 3)

    def test_max_pooling_all_masked_row_stays_finite(self):
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.conf.enums import PoolingType
        from deeplearning4j_tpu.nn.layers.base import get_layer_impl

        impl = get_layer_impl(L.GlobalPoolingLayer(pooling_type=PoolingType.MAX))
        x = jnp.ones((2, 3, 4))
        mask = jnp.asarray([[1.0, 1.0, 0.0], [0.0, 0.0, 0.0]])
        y, _ = impl.forward({}, x, {}, mask=mask)
        assert bool(jnp.all(jnp.isfinite(y)))
        np.testing.assert_allclose(np.asarray(y[1]), np.zeros(4))

    def test_rnn_masked_avg(self):
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.layers.base import get_layer_impl

        impl = get_layer_impl(L.GlobalPoolingLayer())
        x = jnp.asarray([[[1.0, 2.0], [3.0, 4.0], [100.0, 100.0]]])
        mask = jnp.asarray([[1.0, 1.0, 0.0]])
        y, _ = impl.forward({}, x, {}, mask=mask)
        np.testing.assert_allclose(np.asarray(y), [[2.0, 3.0]])


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 10)

    def test_dryrun_multichip_8(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)

    def test_dryrun_multichip_4(self):
        import __graft_entry__ as ge

        ge.dryrun_multichip(4)


class TestTransformerMultiStep:
    def test_fused_k_steps_match_stepwise(self):
        import numpy as np
        import jax
        from deeplearning4j_tpu.models.transformer import TransformerLM

        kw = dict(vocab_size=128, d_model=32, num_heads=4, num_layers=2,
                  max_len=32, seed=3)
        tok = np.random.default_rng(0).integers(0, 128, (2, 32)).astype(
            np.int32)
        a = TransformerLM(**kw).init()
        sa = a.make_train_step(donate=False)
        for _ in range(4):
            a.fit_batch(tok, train_step=sa)
        b = TransformerLM(**kw).init()
        mb = b.make_multi_train_step(4, donate=False)
        b.fit_batch_multi(tok, multi_step=mb, k=4)
        assert a.step_count == b.step_count == 4
        for pa, pb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(pb), np.asarray(pa),
                                       rtol=2e-4, atol=2e-5)


class TestDeviceResidentDataSet:
    def test_dataset_preserves_device_arrays(self):
        import numpy as np
        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.datasets.dataset import DataSet

        x = jax.device_put(np.ones((4, 3), np.float32))
        ds = DataSet(x, [0.0, 1.0, 0.0, 1.0])
        assert isinstance(ds.features, jnp.ndarray)
        assert isinstance(ds.labels, np.ndarray)  # list still coerces


class TestTransformerRemat:
    def test_remat_matches_plain_gradients(self):
        """remat=True recomputes block activations in the backward pass;
        the computed gradients must be bit-identical in structure and
        numerically equal to the plain path."""
        import numpy as np
        import jax
        from deeplearning4j_tpu.models.transformer import TransformerLM

        kw = dict(vocab_size=64, d_model=32, num_heads=4, num_layers=2,
                  max_len=16, seed=5)
        tok = np.random.default_rng(1).integers(0, 64, (2, 16)).astype(
            np.int32)
        plain = TransformerLM(**kw).init()
        remat = TransformerLM(**kw, remat=True).init()
        gp = jax.grad(lambda p: plain.loss(p, tok))(plain.params)
        gr = jax.grad(lambda p: remat.loss(p, tok))(remat.params)
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gr)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_remat_trains(self):
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=32, d_model=32, num_heads=4,
                           num_layers=2, max_len=16, lr=3e-3,
                           dtype_policy="bf16", seed=2, remat=True).init()
        tok = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (4, 1))
        step = lm.make_train_step()
        first = lm.fit_batch(tok, train_step=step)
        for _ in range(40):
            last = lm.fit_batch(tok, train_step=step)
        assert last < first * 0.6


class TestTransformerGenerate:
    @pytest.mark.parametrize("policy", ["float32", "bf16"])
    def test_greedy_matches_full_forward_rerun(self, policy):
        """KV-cache decoding must reproduce the naive decode that re-runs
        the full forward per token (the cache is an optimization, not a
        semantic change) — under BOTH dtype policies: the decode step
        shares _block + dot_product_attention with the forward, so
        accumulation dtypes match."""
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=48, d_model=32, num_heads=4,
                           num_layers=2, max_len=24, seed=11,
                           dtype_policy=policy).init()
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 48, (2, 6)), jnp.int32)
        out = lm.generate(prompt, max_new_tokens=8)
        assert out.shape == (2, 14)

        seq = prompt
        for _ in range(8):
            logits = lm.forward(lm.params, seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_sampling_paths(self):
        import numpy as np
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=32, d_model=32, num_heads=4,
                           num_layers=1, max_len=16, seed=3,
                           dtype_policy="bf16").init()
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 32, (3, 4)), jnp.int32)
        out = lm.generate(prompt, max_new_tokens=5, temperature=0.8,
                          top_k=8, seed=7)
        assert out.shape == (3, 9)
        assert int(out.max()) < 32 and int(out.min()) >= 0
        # prompt is preserved verbatim
        np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                      np.asarray(prompt))
        # same seed reproduces, different seed may differ
        out2 = lm.generate(prompt, max_new_tokens=5, temperature=0.8,
                           top_k=8, seed=7)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_argument_guards(self):
        import pytest as _pytest
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=1, max_len=8, seed=0).init()
        with _pytest.raises(ValueError, match="max_len"):
            lm.make_generate(6, 4)
        with _pytest.raises(ValueError, match="prompt_len"):
            lm.make_generate(0, 4)
        with _pytest.raises(ValueError, match="max_new_tokens"):
            lm.make_generate(4, 0)
        with _pytest.raises(ValueError, match="top_k"):
            lm.make_generate(2, 2, temperature=1.0, top_k=17)
        with _pytest.raises(ValueError, match="top_k"):
            lm.make_generate(2, 2, temperature=1.0, top_k=0)
        with _pytest.raises(ValueError, match="temperature"):
            lm.make_generate(2, 2, temperature=-0.5)


class TestTransformerBeamSearch:
    def _lm(self):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        return TransformerLM(vocab_size=32, d_model=32, num_heads=4,
                             num_layers=2, max_len=24, seed=13).init()

    def test_beam1_equals_greedy(self):
        lm = self._lm()
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, 32, (2, 5)), jnp.int32)
        greedy = lm.generate(prompt, max_new_tokens=7)
        seqs, scores = lm.generate_beam(prompt, max_new_tokens=7,
                                        beam_size=1)
        assert seqs.shape == (2, 1, 12) and scores.shape == (2, 1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                      np.asarray(greedy))

    def test_scores_are_true_log_probs_and_sorted(self):
        """Each beam's score must equal the ACTUAL summed next-token
        log-prob of its sequence under the model (recomputed via the full
        forward), and beams come back best-first."""
        lm = self._lm()
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 32, (1, 4)), jnp.int32)
        p, n = 4, 6
        seqs, scores = lm.generate_beam(prompt, max_new_tokens=n,
                                        beam_size=3)
        s = np.asarray(scores[0])
        assert (np.diff(s) <= 1e-6).all(), "beams not sorted best-first"
        for bi in range(3):
            seq = seqs[0, bi][None]                       # [1, p+n]
            logits = lm.forward(lm.params, seq)
            logp = jax.nn.log_softmax(
                jnp.asarray(logits, jnp.float32), axis=-1)
            # generated tokens sit at positions p..p+n-1, each predicted
            # from the previous position
            tot = sum(float(logp[0, t - 1, int(seq[0, t])])
                      for t in range(p, p + n))
            np.testing.assert_allclose(s[bi], tot, rtol=2e-4, atol=2e-4)

    def test_beams_are_distinct_sequences(self):
        """Distinct (parent, token) extensions of distinct prefixes stay
        distinct: no returned beam may duplicate another."""
        lm = self._lm()
        prompt = jnp.asarray(
            np.random.default_rng(4).integers(0, 32, (3, 4)), jnp.int32)
        seqs, _ = lm.generate_beam(prompt, max_new_tokens=8, beam_size=4)
        for row in np.asarray(seqs):
            uniq = {tuple(beam) for beam in row}
            assert len(uniq) == 4

    def test_beam_guard(self):
        import pytest as _pytest

        lm = self._lm()
        with _pytest.raises(ValueError, match="beam_size"):
            lm.make_generate_beam(4, 4, 33)


class TestRoPE:
    def test_relative_position_property(self):
        """RoPE scores depend only on relative offsets: shifting all
        positions by a constant must leave q·k scores unchanged."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import _rope

        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(1, 6, 2, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, 6, 2, 16)), jnp.float32)
        pos = jnp.arange(6)
        s0 = jnp.einsum("bqhd,bkhd->bhqk", _rope(q, pos), _rope(k, pos))
        s5 = jnp.einsum("bqhd,bkhd->bhqk", _rope(q, pos + 5),
                        _rope(k, pos + 5))
        np.testing.assert_allclose(np.asarray(s5), np.asarray(s0),
                                   rtol=1e-5, atol=1e-5)

    def test_rope_lm_trains_and_decodes(self):
        """A RoPE LM must train, and KV-cache greedy decode must match
        the naive full-forward decode (pins prefill/decode rotation
        consistency at the cache slot)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=2, max_len=32, lr=5e-3, seed=0,
                           pos_encoding="rope").init()
        assert "pos" not in lm.params
        period = 8
        tok = jnp.asarray(np.tile(np.arange(period), (8, 4))[:, :32],
                          jnp.int32)
        step = lm.make_train_step()
        first = lm.fit_batch(tok, train_step=step)
        for _ in range(150):
            last = lm.fit_batch(tok, train_step=step)
        assert last < first * 0.2

        prompt = jnp.asarray(
            np.tile(np.arange(period), (1, 2))[:, :12], jnp.int32)
        out = lm.generate(prompt, max_new_tokens=8)
        seq = prompt
        for _ in range(8):
            logits = lm.forward(lm.params, seq)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))
        # and the trained model continues the cycle
        expect = [(12 + i) % period for i in range(8)]
        assert np.asarray(out)[0, 12:].tolist() == expect

    def test_rope_flash_matches_xla(self):
        import jax
        from deeplearning4j_tpu.models.transformer import TransformerLM

        kw = dict(vocab_size=64, d_model=64, num_heads=4, num_layers=2,
                  max_len=128, seed=7, pos_encoding="rope")
        tok = np.random.default_rng(3).integers(0, 64, (2, 128)).astype(
            np.int32)
        xla = TransformerLM(**kw, attn_impl="xla").init()
        fla = TransformerLM(**kw, attn_impl="flash").init()
        gx = jax.grad(lambda p: xla.loss(p, tok))(xla.params)
        gf = jax.grad(lambda p: fla.loss(p, tok))(fla.params)
        for a, b in zip(jax.tree_util.tree_leaves(gx),
                        jax.tree_util.tree_leaves(gf)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-3, atol=1e-3)

    def test_rope_guards_and_long_decode(self):
        import pytest as _pytest
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        with _pytest.raises(ValueError, match="even head_dim"):
            TransformerLM(vocab_size=16, d_model=96, num_heads=32,
                          pos_encoding="rope")
        # RoPE decodes past max_len (no position table); learned cannot
        rope = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                             num_layers=1, max_len=8, seed=0,
                             pos_encoding="rope").init()
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 16, (1, 6)), jnp.int32)
        out = rope.generate(prompt, max_new_tokens=6)   # total 12 > 8
        assert out.shape == (1, 12)
        learned = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                                num_layers=1, max_len=8, seed=0).init()
        with _pytest.raises(ValueError, match="learned position table"):
            learned.generate(prompt, max_new_tokens=6)


class TestGQA:
    def test_gqa_shapes_and_param_savings(self):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        full = TransformerLM(vocab_size=32, d_model=64, num_heads=8,
                             num_layers=1, max_len=16, seed=0).init()
        gqa = TransformerLM(vocab_size=32, d_model=64, num_heads=8,
                            num_layers=1, max_len=16, seed=0,
                            num_kv_heads=2).init()
        assert gqa.params["blocks"][0]["attn"]["wk"].shape == (64, 16)
        assert full.params["blocks"][0]["attn"]["wk"].shape == (64, 64)

    def test_gqa_trains_and_cache_decode_matches_naive(self):
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        period = 8
        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=2, max_len=32, lr=5e-3, seed=0,
                           num_kv_heads=1, pos_encoding="rope").init()
        tok = jnp.asarray(np.tile(np.arange(period), (8, 4))[:, :32],
                          jnp.int32)
        step = lm.make_train_step()
        first = lm.fit_batch(tok, train_step=step)
        for _ in range(150):
            last = lm.fit_batch(tok, train_step=step)
        assert last < first * 0.2
        prompt = jnp.asarray(
            np.tile(np.arange(period), (1, 2))[:, :12], jnp.int32)
        out = lm.generate(prompt, max_new_tokens=8)
        seq = prompt
        for _ in range(8):
            nxt = jnp.argmax(lm.forward(lm.params, seq)[:, -1],
                             -1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))
        assert np.asarray(out)[0, 12:].tolist() == [
            (12 + i) % period for i in range(8)]

    def test_param_specs_gqa_requires_axis_size(self):
        """Advisor r4: a direct param_specs() call with GQA must not
        default to an unchecked column spec — the validity of sharding
        wk/wv depends on the model-axis size."""
        import pytest as _pytest
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=1, max_len=8, seed=0, num_kv_heads=2)
        with _pytest.raises(ValueError, match="model_axis_size"):
            lm.param_specs()
        wk = lm.param_specs(model_axis_size=2)["blocks"][0]["attn"]["wk"]
        assert wk == P(None, "model")       # 2 kv heads tile axis 2
        wk4 = lm.param_specs(model_axis_size=4)["blocks"][0]["attn"]["wk"]
        assert wk4 == P()                   # 2 % 4 → replicated fallback
        # full-MHA models keep the no-argument call working
        full = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                             num_layers=1, max_len=8, seed=0)
        assert full.param_specs()["blocks"][0]["attn"]["wk"] == \
            P(None, "model")

    def test_gen_cache_lru_bounded(self):
        """Round-4 VERDICT weak #7: the decode compile cache must not
        grow without bound across varying prompt shapes."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=1, max_len=32, seed=0,
                           pos_encoding="rope").init()
        lm.GEN_CACHE_MAX = 2
        for tlen in (2, 3, 4, 5):
            prompt = jnp.zeros((1, tlen), jnp.int32)
            lm.generate(prompt, max_new_tokens=2)
        assert len(lm._gen_cache) == 2
        # most-recent signatures survive
        assert {s[0][1] for s in lm._gen_cache} == {4, 5}

    def test_gqa_guard_and_serialization(self):
        import tempfile

        import pytest as _pytest
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        for bad in (3, 0, -2):
            with _pytest.raises(ValueError, match="num_kv_heads"):
                TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                              num_kv_heads=bad)
        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=1, max_len=8, seed=0,
                           num_kv_heads=2).init()
        with tempfile.TemporaryDirectory() as d:
            ModelSerializer.write_model(lm, f"{d}/g.zip")
            back = ModelSerializer.restore(f"{d}/g.zip")
        assert back.num_kv_heads == 2


class TestSlidingWindowLM:
    def test_windowed_lm_trains_and_decode_matches_naive(self):
        """attn_window LM: the decode step's banded live-mask must equal
        the training-path band — greedy cache decode == naive
        full-forward decode."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM

        period = 8
        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=2, max_len=32, lr=5e-3, seed=0,
                           pos_encoding="rope", attn_window=8).init()
        tok = jnp.asarray(np.tile(np.arange(period), (8, 4))[:, :32],
                          jnp.int32)
        step = lm.make_train_step()
        first = lm.fit_batch(tok, train_step=step)
        for _ in range(150):
            last = lm.fit_batch(tok, train_step=step)
        assert last < first * 0.2
        prompt = jnp.asarray(
            np.tile(np.arange(period), (1, 2))[:, :12], jnp.int32)
        out = lm.generate(prompt, max_new_tokens=8)
        seq = prompt
        for _ in range(8):
            nxt = jnp.argmax(lm.forward(lm.params, seq)[:, -1],
                             -1).astype(jnp.int32)
            seq = jnp.concatenate([seq, nxt[:, None]], 1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))
        assert np.asarray(out)[0, 12:].tolist() == [
            (12 + i) % period for i in range(8)]

    def test_window_guards(self):
        import pytest as _pytest
        from deeplearning4j_tpu.models.transformer import TransformerLM

        with _pytest.raises(ValueError, match="attn_window"):
            TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                          attn_window=0)
        with _pytest.raises(ValueError, match="sp_impl"):
            TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                          sp_impl="frobnicate")

    def test_windowed_sequence_parallel_matches_single_device(self):
        """attn_window now composes with ring attention: the
        sequence-parallel windowed loss must equal the single-device
        windowed loss (round-4 VERDICT weak #3)."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.parallel import MeshSpec, build_mesh

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=8,
                           num_layers=2, max_len=32, seed=0,
                           pos_encoding="rope", attn_window=6).init()
        tok = jnp.asarray(
            np.random.default_rng(0).integers(0, 16, (2, 32)), jnp.int32)
        ref = float(lm.loss(lm.params, tok))
        mesh = build_mesh(MeshSpec(data=1, sequence=8))
        with mesh:
            ring = float(lm.loss(lm.params, tok, mesh=mesh,
                                 sequence_parallel=True))
        assert ring == pytest.approx(ref, rel=1e-5)
        # and the ulysses flavor sees the same band
        uly = TransformerLM(vocab_size=16, d_model=32, num_heads=8,
                            num_layers=2, max_len=32, seed=0,
                            pos_encoding="rope", attn_window=6,
                            sp_impl="ulysses").init()
        with mesh:
            u = float(uly.loss(uly.params, tok, mesh=mesh,
                               sequence_parallel=True))
        assert u == pytest.approx(ref, rel=1e-5)


class TestUlyssesLM:
    """TransformerLM(sp_impl="ulysses") end-to-end (round-4 VERDICT
    weak #4: Ulysses must be reachable from the flagship model)."""

    def _models(self):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        kw = dict(vocab_size=32, d_model=32, num_heads=8, num_layers=2,
                  max_len=32, lr=5e-3, seed=0, pos_encoding="rope")
        return (TransformerLM(sp_impl="ring", **kw).init(),
                TransformerLM(sp_impl="ulysses", **kw).init())

    def test_ulysses_matches_ring_logits(self):
        """Same params, same sharded tokens → same logits from both
        sequence-parallel strategies."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import MeshSpec, build_mesh

        ring_lm, uly_lm = self._models()
        mesh = build_mesh(MeshSpec(data=1, sequence=8))
        tok = jax.device_put(
            jnp.asarray(np.random.default_rng(1).integers(0, 32, (2, 32)),
                        jnp.int32),
            NamedSharding(mesh, P(None, "sequence")))  # dl4j-lint: disable=adhoc-out-shardings -- sequence-axis fixture placement; registry covers data/model/pipe
        with mesh:
            lr = ring_lm.forward(ring_lm.params, tok, mesh=mesh,
                                 sequence_parallel=True)
            lu = uly_lm.forward(uly_lm.params, tok, mesh=mesh,
                                sequence_parallel=True)
        np.testing.assert_allclose(np.asarray(lr), np.asarray(lu),
                                   rtol=2e-4, atol=2e-5)

    def test_ulysses_trains(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel import MeshSpec, build_mesh

        _, uly_lm = self._models()
        mesh = build_mesh(MeshSpec(data=1, sequence=8))
        period = 8
        tok = jax.device_put(
            jnp.asarray(np.tile(np.arange(period), (4, 4)), jnp.int32),
            NamedSharding(mesh, P(None, "sequence")))  # dl4j-lint: disable=adhoc-out-shardings -- sequence-axis fixture placement; registry covers data/model/pipe
        step = uly_lm.make_train_step(mesh=mesh, sequence_parallel=True)
        with mesh:
            first = uly_lm.fit_batch(tok, train_step=step)
            for _ in range(60):
                last = uly_lm.fit_batch(tok, train_step=step)
        assert np.isfinite(last) and last < first * 0.7


class TestTransformerScanLayers:
    """scan_layers=True: the block stack runs as ONE lax.scan over
    stacked per-layer params — the traced program holds one block body
    regardless of depth (the deep serve/bench configs' compile-time
    bound), outputs match the Python-loop path <= 1e-6, and remat
    composes inside the scan body."""

    def _pair(self, depth, **kw):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        cfg = dict(vocab_size=61, d_model=32, num_heads=4,
                   num_layers=depth, max_len=32, seed=1)
        cfg.update(kw)
        return (TransformerLM(**cfg).init(),
                TransformerLM(**cfg, scan_layers=True).init())

    def _toks(self, b=2, t=24):
        return np.random.default_rng(0).integers(
            0, 61, (b, t)).astype(np.int32)

    @pytest.mark.parametrize("depth", [1, 2, 5])
    def test_forward_matches_loop_path(self, depth):
        import jax.numpy as jnp

        loop, scan = self._pair(depth)
        tok = jnp.asarray(self._toks())
        a = np.asarray(loop.forward(loop.params, tok))
        b = np.asarray(scan.forward(scan.params, tok))
        assert np.abs(a - b).max() <= 1e-6

    def test_training_matches_loop_path(self):
        import jax.numpy as jnp

        loop, scan = self._pair(3)
        tok = jnp.asarray(self._toks())
        for _ in range(3):
            la = loop.fit_batch(tok)
            lb = scan.fit_batch(tok)
        assert abs(la - lb) <= 1e-5
        flat_a = jax.tree_util.tree_leaves(loop.params)
        flat_b = jax.tree_util.tree_leaves(scan.params)
        for x, y in zip(flat_a, flat_b):
            assert np.abs(np.asarray(x) - np.asarray(y)).max() <= 1e-5

    def test_block_body_is_depth_invariant(self):
        """The compile-time claim, pinned on the jaxpr: the scan body's
        equation count does not move with num_layers (the loop path
        grows linearly), and the per-layer residue is only the dozen
        trivial stacking ops."""
        import jax
        import jax.numpy as jnp

        tok = jnp.asarray(self._toks())

        def jaxpr_of(lm):
            return jax.make_jaxpr(
                lambda p, t: lm.loss(p, t))(lm.params, tok)

        def body_eqns(j):
            scan_eqn = next(e for e in j.jaxpr.eqns
                            if e.primitive.name == "scan")
            return len(scan_eqn.params["jaxpr"].jaxpr.eqns)

        loop2, scan2 = self._pair(2)
        loop6, scan6 = self._pair(6)
        j2, j6 = jaxpr_of(scan2), jaxpr_of(scan6)
        assert body_eqns(j2) == body_eqns(j6)
        # total residue: stacking plumbing only (~1 eqn per leaf per
        # layer), nothing like the loop path's whole-block growth
        scan_growth = len(j6.jaxpr.eqns) - len(j2.jaxpr.eqns)
        loop_growth = (len(jaxpr_of(loop6).jaxpr.eqns)
                       - len(jaxpr_of(loop2).jaxpr.eqns))
        assert scan_growth * 3 < loop_growth

    def test_remat_composes_inside_scan(self):
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=61, d_model=32, num_heads=4,
                           num_layers=3, max_len=32, seed=1,
                           scan_layers=True, remat=True).init()
        ref = TransformerLM(vocab_size=61, d_model=32, num_heads=4,
                            num_layers=3, max_len=32, seed=1).init()
        tok = jnp.asarray(self._toks())
        g = jax.grad(lambda p: lm.loss(p, tok))(lm.params)
        gr = jax.grad(lambda p: ref.loss(p, tok))(ref.params)
        for x, y in zip(jax.tree_util.tree_leaves(g),
                        jax.tree_util.tree_leaves(gr)):
            assert np.abs(np.asarray(x) - np.asarray(y)).max() <= 1e-5

    def test_get_config_round_trips(self):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        _, scan = self._pair(2)
        assert scan.get_config()["scan_layers"] is True
        back = TransformerLM(**scan.get_config())
        assert back.scan_layers and back.get_config() == scan.get_config()
