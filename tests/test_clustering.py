"""Clustering suite tests: k-means, KDTree, VPTree, QuadTree, SpTree, t-SNE.

Models the reference's test approach (SURVEY §4): small synthetic fixtures,
exact assertions against brute-force ground truth.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree, KMeansClustering, QuadTree, SpTree, VPTree)
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def three_blobs(rng, n_per=40, d=4):
    centers = np.array([[0.0] * d, [10.0] + [0.0] * (d - 1),
                        [0.0, 10.0] + [0.0] * (d - 2)])
    pts = np.concatenate([
        c + rng.normal(0, 0.5, (n_per, d)) for c in centers])
    labels = np.repeat(np.arange(3), n_per)
    return pts.astype(np.float32), labels


class TestKMeans:
    def test_recovers_blobs(self, rng):
        pts, labels = three_blobs(rng)
        cs = KMeansClustering.setup(3, 50).apply_to(pts)
        assert cs.assignments.shape == (120,)
        # each true blob maps to exactly one cluster
        for c in range(3):
            blob_assign = cs.assignments[labels == c]
            assert len(np.unique(blob_assign)) == 1
        # and clusters are distinct across blobs
        reps = [cs.assignments[labels == c][0] for c in range(3)]
        assert len(set(reps)) == 3

    def test_cost_decreases_vs_random_centroids(self, rng):
        pts, _ = three_blobs(rng)
        cs = KMeansClustering(3, max_iterations=50).apply_to(pts)
        one_iter = KMeansClustering(3, max_iterations=1).apply_to(pts)
        assert cs.cost <= one_iter.cost + 1e-3

    def test_cluster_membership_counts(self, rng):
        pts, _ = three_blobs(rng)
        cs = KMeansClustering(3, 50).apply_to(pts)
        assert sum(c.count for c in cs.clusters) == 120

    def test_cosine_distance(self, rng):
        pts, _ = three_blobs(rng)
        cs = KMeansClustering(3, 50, distance="cosine").apply_to(pts)
        assert sum(c.count for c in cs.clusters) == 120

    def test_nearest_cluster(self, rng):
        pts, labels = three_blobs(rng)
        cs = KMeansClustering(3, 50).apply_to(pts)
        idx = cs.nearest_cluster(pts[0])
        assert idx == cs.assignments[0]

    def test_rejects_too_few_points(self):
        with pytest.raises(ValueError):
            KMeansClustering(5, 10).apply_to(np.zeros((3, 2), np.float32))


class TestTrees:
    def test_kdtree_knn_matches_bruteforce(self, rng):
        pts = rng.normal(0, 1, (200, 5))
        tree = KDTree.build(pts)
        q = rng.normal(0, 1, 5)
        got = tree.knn(q, 7)
        d = np.linalg.norm(pts - q[None], axis=1)
        want = np.argsort(d)[:7]
        assert [i for i, _ in got] == list(want)
        np.testing.assert_allclose([dd for _, dd in got], d[want],
                                   rtol=1e-10)

    def test_kdtree_insert_path(self, rng):
        pts = rng.normal(0, 1, (50, 3))
        tree = KDTree(3)
        for i, p in enumerate(pts):
            tree.insert(p, i)
        assert tree.size == 50
        q = rng.normal(0, 1, 3)
        idx, dist = tree.nn(q)
        d = np.linalg.norm(pts - q[None], axis=1)
        assert idx == int(np.argmin(d))

    def test_vptree_knn_matches_bruteforce(self, rng):
        pts = rng.normal(0, 1, (150, 8))
        tree = VPTree(pts)
        q = rng.normal(0, 1, 8)
        got = [i for i, _ in tree.knn(q, 5)]
        d = np.linalg.norm(pts - q[None], axis=1)
        assert got == list(np.argsort(d)[:5])

    def test_vptree_cosine(self, rng):
        pts = rng.normal(0, 1, (100, 6))
        tree = VPTree(pts, distance="cosine")
        q = rng.normal(0, 1, 6)
        got = [i for i, _ in tree.knn(q, 3)]
        sims = (pts @ q) / (np.linalg.norm(pts, axis=1)
                            * np.linalg.norm(q) + 1e-12)
        assert got == list(np.argsort(1.0 - sims)[:3])

    def test_quadtree_range_query(self, rng):
        pts = rng.uniform(-1, 1, (300, 2))
        tree = QuadTree(pts)
        center, hw = (0.2, -0.1), (0.3, 0.25)
        got = tree.query_range(center, hw)
        want = [i for i, p in enumerate(pts)
                if abs(p[0] - center[0]) <= hw[0]
                and abs(p[1] - center[1]) <= hw[1]]
        assert got == sorted(want)

    def test_quadtree_rejects_non_2d(self, rng):
        with pytest.raises(ValueError):
            QuadTree(rng.normal(0, 1, (10, 3)))

    def test_sptree_matches_exact_repulsion(self, rng):
        """theta=0 must reproduce the exact O(n²) repulsive force."""
        y = rng.normal(0, 1, (60, 2))
        tree = SpTree(y)
        neg_f = np.zeros_like(y)
        sum_q = 0.0
        for i in range(60):
            sum_q += tree.compute_non_edge_forces(i, 0.0, neg_f[i])
        # exact
        diff = y[:, None, :] - y[None, :, :]
        d2 = np.sum(diff * diff, axis=-1)
        q = 1.0 / (1.0 + d2)
        np.fill_diagonal(q, 0.0)
        exact_sum_q = q.sum()
        exact_neg = np.einsum("ij,ijk->ik", q * q, diff)
        np.testing.assert_allclose(sum_q, exact_sum_q, rtol=1e-8)
        np.testing.assert_allclose(neg_f, exact_neg, rtol=1e-8, atol=1e-10)

    def test_sptree_theta_approximation_close(self, rng):
        y = rng.normal(0, 1, (120, 2))
        tree = SpTree(y)
        approx = np.zeros_like(y)
        for i in range(120):
            tree.compute_non_edge_forces(i, 0.5, approx[i])
        exact = np.zeros_like(y)
        tree2 = SpTree(y)
        for i in range(120):
            tree2.compute_non_edge_forces(i, 0.0, exact[i])
        err = np.linalg.norm(approx - exact) / np.linalg.norm(exact)
        assert err < 0.1


class TestTsne:
    def test_exact_tsne_separates_blobs(self, rng):
        pts, labels = three_blobs(rng, n_per=30)
        ts = Tsne(perplexity=8, max_iter=250, seed=7)
        y = ts.fit_transform(pts)
        assert y.shape == (90, 2)
        # centroid separation exceeds within-blob spread
        cents = np.stack([y[labels == c].mean(0) for c in range(3)])
        spread = max(np.linalg.norm(y[labels == c] - cents[c], axis=1).mean()
                     for c in range(3))
        min_sep = min(np.linalg.norm(cents[a] - cents[b])
                      for a in range(3) for b in range(a + 1, 3))
        assert min_sep > 2.0 * spread

    def test_exact_tsne_kl_decreases(self, rng):
        pts, _ = three_blobs(rng, n_per=20)
        ts = Tsne(perplexity=8, max_iter=300, seed=3)
        ts.fit_transform(pts)
        assert ts.kl_history[-1] < ts.kl_history[0]

    def test_barnes_hut_separates_blobs(self, rng):
        pts, labels = three_blobs(rng, n_per=25)
        y = BarnesHutTsne(perplexity=8, max_iter=150,
                          seed=7).fit_transform(pts)
        assert y.shape == (75, 2)
        cents = np.stack([y[labels == c].mean(0) for c in range(3)])
        spread = max(np.linalg.norm(y[labels == c] - cents[c], axis=1).mean()
                     for c in range(3))
        min_sep = min(np.linalg.norm(cents[a] - cents[b])
                      for a in range(3) for b in range(a + 1, 3))
        assert min_sep > 1.5 * spread


class TestReviewRegressions:
    """Fixes from code review: cosine VP-tree pruning, duplicate points in
    SpTree, empty-tree errors, zero-iteration k-means."""

    def test_vptree_cosine_many_seeds(self):
        for seed in range(30):
            r = np.random.default_rng(seed)
            pts = r.normal(0, 1, (60, 4))
            q = r.normal(0, 1, 4)
            got = [i for i, _ in VPTree(pts, distance="cosine").knn(q, 5)]
            sims = (pts @ q) / (np.linalg.norm(pts, axis=1)
                                * np.linalg.norm(q) + 1e-12)
            assert got == list(np.argsort(1.0 - sims)[:5]), f"seed {seed}"

    def test_vptree_cosine_distance_values(self, rng):
        pts = rng.normal(0, 1, (40, 3))
        q = rng.normal(0, 1, 3)
        got = VPTree(pts, distance="cosine").knn(q, 3)
        for idx, d in got:
            cos = np.dot(pts[idx], q) / (np.linalg.norm(pts[idx])
                                         * np.linalg.norm(q))
            np.testing.assert_allclose(d, 1.0 - cos, atol=1e-10)

    def test_sptree_duplicate_points(self):
        pts = np.array([[0.0, 0.0], [0.0, 0.0], [1.0, 1.0]])
        tree = SpTree(pts)
        assert tree.root.n_points == 3
        neg = np.zeros(2)
        # self-exclusion: point 0 must still see its duplicate (point 1)
        sum_q = tree.compute_non_edge_forces(0, 0.0, neg)
        # exact: q(0,1)=1/(1+0)=1, q(0,2)=1/(1+2)=1/3
        np.testing.assert_allclose(sum_q, 1.0 + 1.0 / 3.0, rtol=1e-12)

    def test_quadtree_duplicate_points_range_query(self):
        pts = np.array([[0.5, 0.5], [0.5, 0.5], [-0.5, -0.5]])
        tree = QuadTree(pts)
        assert tree.query_range((0.5, 0.5), (0.01, 0.01)) == [0, 1]

    def test_kdtree_empty_nn_raises(self):
        with pytest.raises(ValueError):
            KDTree(3).nn(np.zeros(3))

    def test_kmeans_zero_iterations_rejected(self):
        with pytest.raises(ValueError):
            KMeansClustering(3, max_iterations=0)
