# dl4j-lint: skip-file -- rule-fixture corpus: the snippet strings in this file ARE seeded violations and would (correctly) trip the very rules they test
"""Static-analysis suite tests: the dl4j-lint rule engine and the
fused-program contract checker (deeplearning4j_tpu/analysis/).

Two halves, mirroring the subsystem:

1. **Rule fixtures** — every rule is demonstrated on a known-bad snippet
   (the seeded violation MUST be found), a suppressed variant (inline
   ``# dl4j-lint: disable=<rule> -- reason`` MUST mute it), and a clean
   variant (no false positive). This is the anti-rot harness: a rule
   that silently stops firing fails its positive fixture.
2. **Program contracts** — ``check_network_contracts`` passes on the
   REAL cached fused programs (FF/RNN/graph x {plain, accum, guard,
   telemetry}) and fails on seeded violations: a host callback compiled
   into the program, donation dropped, outputs not matching the program
   key.

The shipped tree itself must be lint-clean: ``scripts/dl4j_lint.py``
exits 0 (also the ``scripts/verify.sh --lint`` gate).
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.analysis import baseline as baseline_mod
from deeplearning4j_tpu.analysis.annotations import HOT_PATH_REGISTRY, traced
from deeplearning4j_tpu.analysis.contracts import (
    ContractViolation,
    callback_primitives,
    check_network_contracts,
    collective_axes,
    donated_arg_indices,
    fused_program_specs,
)
from deeplearning4j_tpu.analysis.engine import (
    LintConfig,
    _parse_pyproject_markers,
    run_lint,
)
from deeplearning4j_tpu.analysis.rules import ALL_RULES
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.perf.epoch_cache import (
    DeviceDataSetCache,
    DeviceMultiDataSetCache,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_CLI = os.path.join(REPO, "scripts", "dl4j_lint.py")


# ---------------------------------------------------------------------------
# fixture-lint harness
# ---------------------------------------------------------------------------


def lint_snippet(tmp_path, source, *, rule=None, relpath="snippet.py",
                 markers=frozenset({"chaos", "slow"})):
    """Write ``source`` at ``relpath`` under a throwaway root and run the
    (optionally selected) ruleset over it; returns the findings."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    config = LintConfig(root=str(tmp_path), registered_markers=set(markers))
    return run_lint(paths=[str(path)],
                    select=None if rule is None else [rule], config=config)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# host-sync-in-hot-path
# ---------------------------------------------------------------------------


class TestHostSyncRule:
    def test_seeded_sync_in_traced_function(self, tmp_path):
        found = lint_snippet(tmp_path, """
            from deeplearning4j_tpu.analysis.annotations import traced

            @traced
            def step(x):
                return float(x.sum())
            """, rule="host-sync-in-hot-path")
        assert len(found) == 1
        assert "float()" in found[0].message
        assert found[0].symbol == "step"

    def test_seeded_sync_via_transitive_callee(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def _epoch_run_fn(xs):
                return helper(xs)

            def helper(xs):
                return xs.item()
            """, rule="host-sync-in-hot-path")
        assert len(found) == 1
        assert found[0].symbol == "helper"  # hot by reachability

    def test_seeded_sync_in_nested_program(self, tmp_path):
        # nested defs run inside the parent's trace (the `run` closure
        # of _epoch_run_fn is the real-tree shape)
        found = lint_snippet(tmp_path, """
            def _epoch_run_fn(self):
                def run(xs):
                    import numpy as np
                    return np.asarray(xs)
                return run
            """, rule="host-sync-in-hot-path")
        assert len(found) == 1
        assert "np.asarray" in found[0].message

    def test_suppressed_with_reason_is_muted(self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def step(x):
                return float(x.sum())  # dl4j-lint: disable=host-sync-in-hot-path -- eager debug helper, never jitted
            """, rule="host-sync-in-hot-path")
        assert found == []

    def test_suppression_without_reason_is_inert_and_reported(
            self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def step(x):
                return float(x.sum())  # dl4j-lint: disable=host-sync-in-hot-path
            """)
        assert "host-sync-in-hot-path" in rules_of(found)
        assert "suppression-missing-reason" in rules_of(found)

    def test_clean_cold_function_and_host_scalars(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def cold_report(x):
                return float(x.sum())  # not reachable from a hot root

            @traced
            def step(xs):
                return xs * (1.0 / float(len(xs)))  # host scalar, no sync
            """, rule="host-sync-in-hot-path")
        assert found == []

    def test_seeded_sync_inside_lambda(self, tmp_path):
        # a lambda closed over inside a traced function runs inside the
        # trace exactly like a nested def — closure syntax must not
        # change coverage
        found = lint_snippet(tmp_path, """
            @traced
            def hot(xs):
                f = lambda v: float(v)
                return [f(x) for x in xs]
            """, rule="host-sync-in-hot-path")
        assert len(found) == 1
        assert "<lambda>" in found[0].message

    def test_registry_names_still_defined(self):
        """The registry must not rot: every listed hot root exists in the
        tree (a rename without updating the registry silently un-hots the
        function)."""
        import ast

        defined = set()
        for sub in ("nn", "perf", "monitor", "resilience", "serving",
                    "nlp"):
            base = os.path.join(REPO, "deeplearning4j_tpu", sub)
            for root, _, files in os.walk(base):
                for name in files:
                    if not name.endswith(".py"):
                        continue
                    with open(os.path.join(root, name),
                              encoding="utf-8") as f:
                        tree = ast.parse(f.read())
                    defined |= {n.name for n in ast.walk(tree)
                                if isinstance(n, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef))}
        missing = HOT_PATH_REGISTRY - defined
        assert not missing, f"registry names without a definition: {missing}"

    def test_traced_is_identity_at_runtime(self):
        def f(x):
            return x + 1

        g = traced(f)
        assert g is f and g.__dl4j_traced__ and g(1) == 2


# ---------------------------------------------------------------------------
# implicit-f32-promotion
# ---------------------------------------------------------------------------


class TestImplicitF32PromotionRule:
    """A matmul/einsum operand reaching a param leaf without
    ``policy.cast_compute`` inside a traced hot path — the bug class
    that already shipped once (the transformer residual-stream f32
    promotion under the bf16 policy)."""

    def test_seeded_raw_leaf_operand(self, tmp_path):
        found = lint_snippet(tmp_path, """
            from deeplearning4j_tpu.analysis.annotations import traced

            @traced
            def _block(self, blk, h):
                return h @ blk["attn"]["wq"]
            """, rule="implicit-f32-promotion")
        assert len(found) == 1
        assert "blk['attn']['wq']" in found[0].message
        assert found[0].symbol == "_block"

    def test_seeded_bound_name_and_einsum(self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def _step_impl(params, x):
                w1 = params["mlp"]["w1"]
                a = x @ w1
                b = jnp.einsum("bd,df->bf", a, params["w3"])
                return a + b
            """, rule="implicit-f32-promotion")
        assert len(found) == 2
        assert {"w1" in f.message or "w3" in f.message
                for f in found} == {True}

    def test_seeded_in_hot_registry_root(self, tmp_path):
        # HOT_PATH_REGISTRY names are hot without the decorator
        found = lint_snippet(tmp_path, """
            def _epoch_run_fn(self, params, x):
                return lax.dot_general(x, params["W"], dims)
            """, rule="implicit-f32-promotion")
        assert len(found) == 1

    def test_cast_compute_wrapped_operand_is_clean(self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def _block(self, policy, blk, h):
                q = h @ policy.cast_compute(blk["attn"]["wq"])
                w = policy.cast_compute(blk["mlp"]["w1"])
                z = q @ w
                return z @ blk["out"]["w2"].astype(h.dtype)
            """, rule="implicit-f32-promotion")
        assert found == []

    def test_cold_function_and_data_subscripts_are_clean(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def cold(params, x):
                return x @ params["W"]   # not reachable from a hot root

            @traced
            def _step_impl(xs, i, w_cast):
                return xs[i] @ w_cast    # integer gather = data, not params
            """, rule="implicit-f32-promotion")
        assert found == []

    def test_suppressed_with_reason_is_muted(self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def _step_impl(params, x):
                return x @ params["W"]  # dl4j-lint: disable=implicit-f32-promotion -- f64 gradient-check path, promotion intended
            """, rule="implicit-f32-promotion")
        assert found == []

    def test_shipped_tree_is_clean(self):
        # the matmul-heavy hot surfaces; the default full-tree CLI run
        # in this suite already covers the rule over everything else
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--select",
             "implicit-f32-promotion",
             os.path.join(REPO, "deeplearning4j_tpu", "models"),
             os.path.join(REPO, "deeplearning4j_tpu", "nn"),
             os.path.join(REPO, "deeplearning4j_tpu", "serving"),
             os.path.join(REPO, "deeplearning4j_tpu", "pallas")],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------


class TestRecompileHazardRule:
    def test_seeded_list_in_cache_key(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Net:
                def lookup(self, shuffle, dims):
                    key = (shuffle, list(dims))
                    return self._epoch_steps.get(key)
            """, rule="recompile-hazard")
        assert len(found) == 1
        assert "_epoch_steps" in found[0].message

    def test_seeded_lambda_in_subscript_key(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Net:
                def store(self, shuffle, fn):
                    self._program_cache[(shuffle, lambda: fn)] = fn
            """, rule="recompile-hazard")
        assert len(found) == 1
        assert "lambda" in found[0].message

    def test_suppressed(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Net:
                def lookup(self, shuffle, dims):
                    key = (shuffle, list(dims))  # dl4j-lint: disable=recompile-hazard -- interned upstream, single instance
                    return self._epoch_steps.get(key)
            """, rule="recompile-hazard")
        assert found == []

    def test_clean_hashable_key(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Net:
                def lookup(self, shuffle, accum, guard, stride):
                    key = (shuffle, int(accum), bool(guard), stride)
                    return self._epoch_steps.get(key)
            """, rule="recompile-hazard")
        assert found == []

    def test_rebinding_resolves_to_latest_assignment(self, tmp_path):
        # hashable at use: list -> tuple rebind must NOT be flagged
        clean = lint_snippet(tmp_path, """
            class Net:
                def lookup(self, dims):
                    key = list(dims)
                    key = tuple(key)
                    return self._epoch_steps.get(key)
            """, rule="recompile-hazard")
        assert clean == []
        # unhashable at use: tuple -> list rebind MUST be flagged
        found = lint_snippet(tmp_path, """
            class Net:
                def lookup(self, a, b):
                    key = (a, b)
                    key = list(key)
                    return self._epoch_steps.get(key)
            """, rule="recompile-hazard")
        assert len(found) == 1
        assert "list" in found[0].message


# ---------------------------------------------------------------------------
# rng-reuse
# ---------------------------------------------------------------------------


class TestRngReuseRule:
    def test_seeded_double_consumption(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """, rule="rng-reuse")
        assert len(found) == 1
        assert "consumed again" in found[0].message
        assert found[0].line == 6  # the second consumer

    def test_seeded_reuse_across_loop_iterations(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key, n):
                out = []
                for _ in range(n):
                    out.append(jax.random.normal(key, (3,)))
                return out
            """, rule="rng-reuse")
        assert len(found) == 1

    def test_suppressed(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))  # dl4j-lint: disable=rng-reuse -- correlated draws are the point here
                return a + b
            """, rule="rng-reuse")
        assert found == []

    def test_clean_split_and_branches(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                k1, k2 = jax.random.split(key)
                return jax.random.normal(k1, (3,)) + jax.random.uniform(
                    k2, (3,))

            def branchy(key, flag):
                if flag:
                    return jax.random.normal(key, (3,))
                return jax.random.uniform(key, (3,))

            def rebound(key):
                sub, key = jax.random.split(key)
                a = jax.random.normal(sub, (3,))
                sub, key = jax.random.split(key)
                return a + jax.random.normal(sub, (3,))
            """, rule="rng-reuse")
        assert found == []

    def test_seeded_reuse_of_underscore_attr_key(self, tmp_path):
        # the networks' key attribute is self._rng: the leading
        # underscore must not hide reuse from the rule
        found = lint_snippet(tmp_path, """
            import jax

            class Net:
                def draw(self):
                    a = jax.random.normal(self._rng, (3,))
                    b = jax.random.uniform(self._rng, (3,))
                    return a + b
            """, rule="rng-reuse")
        assert len(found) == 1
        assert "self._rng" in found[0].message

    def test_clean_split_then_reassign_attr_key(self, tmp_path):
        # the codebase idiom: split, reassign self._rng, consume keys
        found = lint_snippet(tmp_path, """
            import jax

            class Net:
                def draw(self, n):
                    keys = jax.random.split(self._rng, n + 1)
                    self._rng = keys[0]
                    return jax.random.normal(keys[1], (3,))
            """, rule="rng-reuse")
        assert found == []

    def test_seeded_reuse_inside_match_case(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key, mode):
                match mode:
                    case "a":
                        a = jax.random.normal(key, (3,))
                        b = jax.random.normal(key, (3,))
                        return a + b
                    case _:
                        return jax.random.uniform(key, (3,))
            """, rule="rng-reuse")
        assert len(found) == 1

    def test_clean_exclusive_match_cases(self, tmp_path):
        # one consumer per case: cases are mutually exclusive branches
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key, mode):
                match mode:
                    case "a":
                        out = jax.random.normal(key, (3,))
                    case _:
                        out = jax.random.uniform(key, (3,))
                return out
            """, rule="rng-reuse")
        assert found == []

    def test_clean_try_except_fallback(self, tmp_path):
        # try body and handler are mutually exclusive: only ONE consumer
        # ever draws from the key, like an If branch pair
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                try:
                    out = jax.random.normal(key, (3,))
                except Exception:
                    out = jax.random.uniform(key, (3,))
                return out
            """, rule="rng-reuse")
        assert found == []

    def test_seeded_reuse_after_try_still_caught(self, tmp_path):
        # consumption inside try (or its handler) still counts against a
        # consumer AFTER the statement
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                try:
                    a = jax.random.normal(key, (3,))
                except Exception:
                    a = 0.0
                return a + jax.random.uniform(key, (3,))
            """, rule="rng-reuse")
        assert len(found) == 1


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


LOCK_BAD = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.progress = 0

        def start(self):
            self._thread = threading.Thread(target=self._run)
            self._thread.start()

        def _run(self):
            self.progress = 1{bg_suffix}

        def stop(self):
            {fg_write}
"""


class TestLockDisciplineRule:
    def test_seeded_unlocked_cross_thread_write(self, tmp_path):
        found = lint_snippet(
            tmp_path,
            LOCK_BAD.format(bg_suffix="", fg_write="self.progress = 2"),
            rule="lock-discipline")
        # one finding PER unlocked site (bg + fg): suppressing one site
        # must never silence the other
        assert len(found) == 2
        assert all("Worker.progress" in f.message for f in found)

    def test_seeded_write_from_submit_closure(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Pool:
                def kick(self, executor):
                    def job():
                        self.result = 42
                    executor.submit(job)

                def reset(self):
                    self.result = None
            """, rule="lock-discipline")
        assert len(found) == 2  # one per unlocked site (closure + reset)
        assert all("Pool.result" in f.message for f in found)

    def test_suppressed(self, tmp_path):
        found = lint_snippet(
            tmp_path,
            LOCK_BAD.format(
                bg_suffix=("  # dl4j-lint: disable=lock-discipline -- "
                           "joined before any foreground read"),
                fg_write=("self.progress = 2  # dl4j-lint: "
                          "disable=lock-discipline -- thread joined "
                          "before stop() can run")),
            rule="lock-discipline")
        assert found == []

    def test_suppressing_one_site_leaves_others_reported(self, tmp_path):
        # the preemption.py hazard class: a justified suppression on the
        # signal-handler write must NOT silence a different, unlocked
        # write of the same attribute from another context
        found = lint_snippet(
            tmp_path,
            LOCK_BAD.format(
                bg_suffix=("  # dl4j-lint: disable=lock-discipline -- "
                           "joined before any foreground read"),
                fg_write="self.progress = 2"),
            rule="lock-discipline")
        assert len(found) == 1
        assert "'stop'" in found[0].message

    def test_clean_locked_writes(self, tmp_path):
        found = lint_snippet(
            tmp_path,
            LOCK_BAD.format(bg_suffix="", fg_write=(
                "with self._lock:\n                self.progress = 2")),
            rule="lock-discipline")
        # bg write unlocked but fg locked -> still a finding? No: the
        # rule fires only when there is at least one UNLOCKED write AND
        # >= 2 contexts; make both locked to be clean
        found2 = lint_snippet(tmp_path, """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.progress = 0

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    with self._lock:
                        self.progress = 1

                def stop(self):
                    with self._lock:
                        self.progress = 2
            """, rule="lock-discipline")
        assert found2 == []
        assert len(found) == 1  # half-locked is still a race

    def test_clean_single_thread_attribute(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self.bg_only = 1
                    self.bg_only += 1

                def status(self):
                    return "running"
            """, rule="lock-discipline")
        assert found == []


# ---------------------------------------------------------------------------
# donation-consistency
# ---------------------------------------------------------------------------


class TestDonationConsistencyRule:
    def test_seeded_read_after_donation(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def train(params, grads):
                step = jax.jit(apply_fn, donate_argnums=(0,))
                new_params = step(params, grads)
                return new_params, params
            """, rule="donation-consistency")
        assert len(found) == 1
        assert "'params' was donated" in found[0].message

    def test_seeded_read_after_partial_decorated_donation(self, tmp_path):
        # the codebase's @functools.partial(jax.jit, donate_argnums=...)
        # idiom (glove/word2vec/kmeans) must be tracked like jax.jit(...)
        found = lint_snippet(tmp_path, """
            import functools
            import jax

            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(params, x):
                return params

            def run(params, x):
                q = step(params, x)
                return params
            """, rule="donation-consistency")
        assert len(found) == 1
        assert "'params'" in found[0].message

    def test_conditional_donate_argnums_not_tracked(self, tmp_path):
        # `donate_argnums=(0, 1) if donate else ()` is indeterminate:
        # the read after the call is legal whenever donate=False and
        # must not be flagged
        found = lint_snippet(tmp_path, """
            import jax

            def build(step, donate, a, b):
                fn = jax.jit(step, donate_argnums=(0, 1) if donate
                             else ())
                out = fn(a, b)
                return a + out
            """, rule="donation-consistency")
        assert found == []

    def test_seeded_read_after_known_donating_method(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def fit(self, batch):
                out = self._train_step(self.params, self.updater_state,
                                       self.net_state, batch)
                norm = tree_norm(self.params)
                return out, norm
            """, rule="donation-consistency")
        assert len(found) == 1
        assert "self.params" in found[0].message

    def test_suppressed(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def train(params, grads):
                step = jax.jit(apply_fn, donate_argnums=(0,))
                new_params = step(params, grads)
                return new_params, params  # dl4j-lint: disable=donation-consistency -- CPU backend never aliases
            """, rule="donation-consistency")
        assert found == []

    def test_clean_rebinding_clears_poison(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def train(params, grads):
                step = jax.jit(apply_fn, donate_argnums=(0,))
                params = step(params, grads)
                return params

            def fit(self, batch):
                (self.params, self.updater_state, self.net_state,
                 _, loss) = self._train_step(
                    self.params, self.updater_state, self.net_state, batch)
                return self.params, loss
            """, rule="donation-consistency")
        assert found == []


# ---------------------------------------------------------------------------
# bare-counter (the absorbed scripts/lint_telemetry.py)
# ---------------------------------------------------------------------------


class TestBareCounterRule:
    def test_seeded_bare_counter_outside_monitor(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Cache:
                def __init__(self):
                    self._rebuild_counter = 0
            """, rule="bare-counter",
            relpath="deeplearning4j_tpu/perf/cache_x.py")
        assert len(found) == 1
        assert "_rebuild_counter" in found[0].message

    def test_suppressed(self, tmp_path):
        found = lint_snippet(tmp_path, """
            class Cache:
                def __init__(self):
                    self._rebuild_counter = 0  # dl4j-lint: disable=bare-counter -- mirrored into the registry below
            """, rule="bare-counter",
            relpath="deeplearning4j_tpu/perf/cache_x.py")
        assert found == []

    def test_clean_inside_monitor_and_outside_package(self, tmp_path):
        src = """
            class Cache:
                def __init__(self):
                    self._rebuild_counter = 0
            """
        assert lint_snippet(
            tmp_path, src, rule="bare-counter",
            relpath="deeplearning4j_tpu/monitor/cache_x.py") == []
        assert lint_snippet(
            tmp_path, src, rule="bare-counter",
            relpath="tests/helper_x.py") == []

    def test_absorbs_old_cli_contract(self):
        """The --select bare-counter CLI run is what verify.sh --obs now
        invokes in place of the deleted scripts/lint_telemetry.py; the
        shipped tree must be clean under it."""
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--select", "bare-counter"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        assert not os.path.exists(
            os.path.join(REPO, "scripts", "lint_telemetry.py"))


# ---------------------------------------------------------------------------
# marker-audit
# ---------------------------------------------------------------------------


class TestMarkerAuditRule:
    def test_seeded_chaos_behavior_without_marker(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def test_survives_faults():
                from deeplearning4j_tpu.resilience import faults
                faults.install_from_env()
            """, rule="marker-audit", relpath="tests/test_x.py")
        assert len(found) == 1
        assert "chaos" in found[0].message

    def test_seeded_unregistered_marker(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import pytest

            @pytest.mark.gpu_only
            def test_thing():
                pass
            """, rule="marker-audit", relpath="tests/test_x.py")
        assert len(found) == 1
        assert "gpu_only" in found[0].message

    def test_seeded_long_sleep_without_slow(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import time

            def test_settles():
                time.sleep(2)
            """, rule="marker-audit", relpath="tests/test_x.py")
        assert len(found) == 1
        assert "slow" in found[0].message

    def test_docstring_mention_does_not_demand_chaos_marker(
            self, tmp_path):
        # detection is AST-based: prose that MENTIONS fault_point() or
        # DL4J_FAULTS (docstrings, comments) is not fault injection
        found = lint_snippet(tmp_path, '''
            def test_plain_path():
                """Unlike fault_point()-driven chaos cases or the
                DL4J_FAULTS env spec, this exercises the no-op path."""
                # fault_point() deliberately NOT called here
                assert 1 + 1 == 2
            ''', rule="marker-audit",
            relpath="tests/test_snip.py")
        assert found == []

    def test_env_string_constant_still_detected(self, tmp_path):
        found = lint_snippet(tmp_path, """
            def test_envvar(monkeypatch):
                monkeypatch.setenv("DL4J_FAULTS", "site:fail:1")
            """, rule="marker-audit",
            relpath="tests/test_snip.py")
        assert len(found) == 1
        assert "chaos" in found[0].message

    def test_clean_marked_variants(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import time

            import pytest

            @pytest.mark.chaos
            def test_survives_faults():
                from deeplearning4j_tpu.resilience import faults
                faults.install_from_env()

            @pytest.mark.slow
            def test_settles():
                time.sleep(2)

            def test_quick_nap():
                time.sleep(0.05)
            """, rule="marker-audit", relpath="tests/test_x.py")
        assert found == []

    def test_class_and_module_level_marks_cover(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import pytest

            pytestmark = pytest.mark.chaos

            class TestFaulty:
                def test_one(self):
                    from deeplearning4j_tpu.resilience import faults
                    faults.install_from_env()
            """, rule="marker-audit", relpath="tests/test_x.py")
        assert found == []

    def test_non_test_files_ignored(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import pytest

            @pytest.mark.anything_goes
            def test_helper():
                pass
            """, rule="marker-audit", relpath="tests/helpers.py")
        assert found == []

    def test_marker_parse_survives_bracket_and_quotes_in_descriptions(
            self, tmp_path):
        # a ']' inside a description must not truncate the list, and
        # quoted words in descriptions must not register as markers
        py = tmp_path / "pyproject.toml"
        py.write_text(
            '[tool.pytest.ini_options]\n'
            'markers = [\n'
            '    "gpu: [experimental] gpu-only tests",\n'
            '    "chaos: uses the \'faults\' module",\n'
            '    "slow: long-running",\n'
            ']\n')
        from deeplearning4j_tpu.analysis.engine import (
            _parse_pyproject_markers,
        )
        assert _parse_pyproject_markers(str(py)) == {
            "gpu", "chaos", "slow"}

    def test_real_pyproject_markers_parse(self):
        markers = _parse_pyproject_markers(
            os.path.join(REPO, "pyproject.toml"))
        assert {"slow", "chaos"} <= markers


# ---------------------------------------------------------------------------
# adhoc-out-shardings
# ---------------------------------------------------------------------------


class TestAdhocOutShardingsRule:
    def test_seeded_named_sharding_ctor(self, tmp_path):
        found = lint_snippet(tmp_path, """
            from jax.sharding import NamedSharding, PartitionSpec as P

            def place(mesh, x):
                import jax
                return jax.device_put(x, NamedSharding(mesh, P("data")))
            """, rule="adhoc-out-shardings",
            relpath="deeplearning4j_tpu/perf/place_x.py")
        assert len(found) == 1
        assert "NamedSharding" in found[0].message

    def test_seeded_dotted_ctor_and_out_shardings_kwarg(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def compile_step(mesh, fn, sh):
                pin = jax.sharding.NamedSharding(mesh, sh)
                return jax.jit(fn, out_shardings=pin)
            """, rule="adhoc-out-shardings",
            relpath="deeplearning4j_tpu/perf/pin_x.py")
        assert len(found) == 2
        msgs = " ".join(f.message for f in found)
        assert "NamedSharding" in msgs and "out_shardings" in msgs

    def test_registry_module_itself_exempt(self, tmp_path):
        found = lint_snippet(tmp_path, """
            from jax.sharding import NamedSharding

            def named(mesh, spec):
                return NamedSharding(mesh, spec)
            """, rule="adhoc-out-shardings",
            relpath="deeplearning4j_tpu/parallel/sharding_registry.py")
        assert found == []

    def test_def_header_suppression(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def shard_raw(mesh, x, sh):  # dl4j-lint: disable=adhoc-out-shardings -- sanctioned low-level builder
                return jax.device_put(
                    x, jax.sharding.NamedSharding(mesh, sh))
            """, rule="adhoc-out-shardings",
            relpath="deeplearning4j_tpu/parallel/mesh_x.py")
        assert found == []

    def test_registry_sourced_shardings_clean(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def compile_step(reg, fn, params):
                shardings = reg.param_shardings(params)
                return jax.jit(fn), shardings
            """, rule="adhoc-out-shardings",
            relpath="deeplearning4j_tpu/perf/clean_x.py")
        assert found == []

    def test_shipped_tree_clean_under_select(self):
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--select", "adhoc-out-shardings"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# engine: suppressions, baseline, CLI
# ---------------------------------------------------------------------------


class TestEngineAndBaseline:
    def test_def_header_suppression_covers_body(self, tmp_path):
        found = lint_snippet(tmp_path, """
            @traced
            def step(x):  # dl4j-lint: disable=host-sync-in-hot-path -- eager-only reference impl
                a = float(x.sum())
                b = x.item()
                return a + b
            """, rule="host-sync-in-hot-path")
        assert found == []

    def test_own_decorator_line_suppresses_def_anchored_finding(
            self, tmp_path):
        """marker-audit anchors ON the def node; a suppression riding the
        function's OWN decorator line must cover it (docs: 'On a
        def/class header (or one of its decorator lines)')."""
        src = """
            import time
            import pytest

            @pytest.mark.parametrize("n", [1])  # dl4j-lint: disable=marker-audit -- fixture: tier-1 never collects this module
            def test_nap(n):
                time.sleep(2.0)
            """
        found = lint_snippet(tmp_path, src, rule="marker-audit",
                             relpath="tests/test_snip.py")
        assert found == []

    def test_pragma_quoted_in_docstring_is_inert(self, tmp_path):
        """Pragmas live in COMMENT tokens only: a module docstring that
        QUOTES the skip-file / disable syntax (usage docs) must neither
        skip the file nor suppress anything."""
        found = lint_snippet(tmp_path, '''
            """Usage example:

                # dl4j-lint: skip-file -- fixture corpus
                # dl4j-lint: disable=rng-reuse -- correlated on purpose
            """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            ''', rule="rng-reuse")
        assert len(found) == 1

    def test_suppression_on_closing_line_of_multiline_stmt(
            self, tmp_path):
        """The natural place for the comment is the statement's LAST
        line; it must suppress findings anchored on the first."""
        found = lint_snippet(tmp_path, """
            import threading

            class Worker:
                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self.state = (
                        "running",
                        1)  # dl4j-lint: disable=lock-discipline -- joined before any reader

                def stop(self):
                    self.state = None  # dl4j-lint: disable=lock-discipline -- thread joined first
            """, rule="lock-discipline")
        assert found == []

    def test_disable_all_mutes_every_rule(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                return a + jax.random.uniform(key, (3,))  # dl4j-lint: disable=all -- fixture for the docs example
            """)
        assert found == []

    def test_parse_error_is_a_finding_not_a_crash(self, tmp_path):
        found = lint_snippet(tmp_path, "def broken(:\n")
        assert rules_of(found) == ["parse-error"]

    def test_skip_file_pragma_mutes_all_rules(self, tmp_path):
        found = lint_snippet(tmp_path, """
            # dl4j-lint: skip-file -- fixture corpus for the engine test
            import jax

            @traced
            def step(key):
                a = jax.random.normal(key, (3,))
                return float(a.sum()) + float(
                    jax.random.uniform(key, ()).sum())
            """)
        assert found == []

    def test_skip_file_without_reason_is_inert_and_reported(
            self, tmp_path):
        found = lint_snippet(tmp_path, """
            # dl4j-lint: skip-file
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                return a + jax.random.uniform(key, (3,))
            """)
        assert "rng-reuse" in rules_of(found)  # pragma did NOT apply
        assert any(f.rule == "suppression-missing-reason"
                   and "skip-file" in f.message for f in found)

    def test_skip_file_pragma_only_scanned_near_top(self, tmp_path):
        found = lint_snippet(tmp_path, """
            import jax


            def filler_a():
                return 1


            def filler_b():
                return 2


            def sample(key):
                # dl4j-lint: skip-file -- buried too deep to count
                a = jax.random.normal(key, (3,))
                return a + jax.random.uniform(key, (3,))
            """)
        assert "rng-reuse" in rules_of(found)

    def test_fingerprint_survives_unrelated_edits(self, tmp_path):
        src = """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """
        (f1,) = lint_snippet(tmp_path, src, rule="rng-reuse")
        fp1 = baseline_mod.fingerprint(f1, root=str(tmp_path))
        # prepend lines: the finding moves but its fingerprint must not
        shifted = "'''module docstring'''\nX = 1\n" + textwrap.dedent(src)
        (tmp_path / "snippet.py").write_text(shifted)
        config = LintConfig(root=str(tmp_path),
                            registered_markers={"chaos", "slow"})
        (f2,) = run_lint(paths=[str(tmp_path / "snippet.py")],
                         select=["rng-reuse"], config=config)
        assert f2.line != f1.line
        assert baseline_mod.fingerprint(f2, root=str(tmp_path)) == fp1

    def test_baseline_roundtrip_and_partition(self, tmp_path):
        findings = lint_snippet(tmp_path, """
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """, rule="rng-reuse")
        path = str(tmp_path / "baseline.json")
        assert baseline_mod.save_baseline(
            findings, path=path, root=str(tmp_path)) == 1
        loaded = baseline_mod.load_baseline(path)
        new, old = baseline_mod.partition_findings(
            findings, loaded, root=str(tmp_path))
        assert new == [] and old == findings

    def test_load_baseline_tolerates_absent_and_garbage(self, tmp_path):
        assert baseline_mod.load_baseline(str(tmp_path / "nope.json")) == {}
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert baseline_mod.load_baseline(str(bad)) == {}

    def test_shipped_tree_is_lint_clean(self):
        """THE gate: scripts/verify.sh --lint runs exactly this and the
        contract suite; the shipped tree must exit 0."""
        proc = subprocess.run([sys.executable, LINT_CLI],
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_cli_reports_seeded_violation_and_baseline_flow(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """))
        base = str(tmp_path / "baseline.json")
        run = lambda *extra: subprocess.run(  # noqa: E731
            [sys.executable, LINT_CLI, "--baseline", base, str(bad),
             *extra], capture_output=True, text=True)
        first = run()
        assert first.returncode == 1
        assert "rng-reuse" in first.stderr
        assert run("--update-baseline").returncode == 0
        adopted = run()
        assert adopted.returncode == 0
        assert "baselined" in adopted.stdout
        # a NEW finding still fails even with the baseline in place
        bad.write_text(bad.read_text() + textwrap.dedent("""
            def sample2(rng):
                a = jax.random.normal(rng, (3,))
                b = jax.random.uniform(rng, (3,))
                return a + b
            """))
        again = run()
        assert again.returncode == 1
        assert "1 new finding" in again.stderr

    def test_partial_update_baseline_preserves_other_entries(self, tmp_path):
        """A --select/path-narrowed --update-baseline replaces only the
        slice it re-scanned; other rules'/paths' entries survive."""
        one = tmp_path / "one.py"
        one.write_text(textwrap.dedent("""
            import jax

            def sample(key):
                a = jax.random.normal(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
            """))
        two = tmp_path / "two.py"
        two.write_text(textwrap.dedent("""
            class Net:
                def lookup(self, dims):
                    return self._epoch_steps.get((1, list(dims)))
            """))
        base = str(tmp_path / "baseline.json")
        run = lambda *argv: subprocess.run(  # noqa: E731
            [sys.executable, LINT_CLI, "--baseline", base, *argv],
            capture_output=True, text=True)
        # adopt one.py's backlog (path-narrowed update)
        assert run(str(one), "--update-baseline").returncode == 0
        # then adopt two.py's via a RULE-narrowed update over both paths:
        # one.py's rng-reuse entry must not be discarded
        assert run("--select", "recompile-hazard", str(one), str(two),
                   "--update-baseline").returncode == 0
        final = run(str(one), str(two))
        assert final.returncode == 0, final.stderr
        assert "baselined" in final.stdout

    def test_cli_nonexistent_path_exits_2(self):
        """A typo'd path must not turn the gate vacuous: scanning zero
        files is an error, not an OK."""
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "no-such-dir-typo"],
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "do not exist" in proc.stderr

    def test_cli_empty_dir_exits_2(self, tmp_path):
        """An existing path with zero Python files is equally vacuous."""
        empty = tmp_path / "empty"
        empty.mkdir()
        proc = subprocess.run(
            [sys.executable, LINT_CLI, str(empty)],
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "nothing was checked" in proc.stderr

    def test_cli_empty_select_exits_2(self):
        """`--select ""` (an unset shell variable) must not match zero
        rules and report the tree clean."""
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--select", ""],
            capture_output=True, text=True)
        assert proc.returncode == 2
        assert "names no rules" in proc.stderr

    def test_annotations_import_stays_engine_free(self):
        """Production modules import @traced at module level; that must
        not load the lint engine (ast/tokenize machinery) or jax."""
        code = ("import sys\n"
                "from deeplearning4j_tpu.analysis.annotations import "
                "traced\n"
                "bad = [m for m in sys.modules if "
                "m.endswith('analysis.engine') or "
                "m.endswith('analysis.contracts') or m == 'jax']\n"
                "assert not bad, bad\n")
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr

    def test_cli_unknown_rule_exits_2(self):
        proc = subprocess.run(
            [sys.executable, LINT_CLI, "--select", "no-such-rule"],
            capture_output=True, text=True)
        assert proc.returncode == 2

    def test_cli_list_rules_names_whole_catalog(self):
        proc = subprocess.run([sys.executable, LINT_CLI, "--list-rules"],
                              capture_output=True, text=True)
        assert proc.returncode == 0
        for rule in ALL_RULES:
            assert rule.id in proc.stdout


# ---------------------------------------------------------------------------
# program contracts
# ---------------------------------------------------------------------------


def _ff_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
        .updater(Updater.SGD).list()
        .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build()).init()


def _ff_data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _rnn_data(n=24, t=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    return DataSet(x, y)


# the FF/RNN/graph x {plain, accum, guard, telemetry} matrix of ISSUE 7:
# every program variant the fused pipeline can cache, as its
# (shuffle, accum_steps, guard, metrics_stride) key
PROGRAM_VARIANTS = (
    (True, 1, False, 0),   # plain
    (False, 2, False, 0),  # accumulated
    (True, 1, True, 0),    # sentinel-guarded (the fit_epochs default)
    (True, 1, False, 1),   # telemetry pack
    (True, 1, True, 2),    # guard + strided pack composed
)


def _net_and_cache(kind):
    if kind == "ff":
        net = _ff_net()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_ff_data(), batch_size=16))
    elif kind == "rnn":
        net = _rnn_net()
        cache = DeviceDataSetCache.build(
            ListDataSetIterator(_rnn_data(), batch_size=8))
    else:
        net = _ff_graph()
        cache = DeviceMultiDataSetCache.build(
            ListDataSetIterator(_ff_data(), batch_size=16))
    assert cache is not None
    return net, cache


class TestProgramContracts:
    @pytest.mark.parametrize("kind", ["ff", "rnn", "graph"])
    def test_all_cached_variants_satisfy_contract(self, kind):
        net, cache = _net_and_cache(kind)
        for key in PROGRAM_VARIANTS:
            net._epoch_train_step(*key)
        results = check_network_contracts(net, cache)
        assert sorted(results) == sorted(PROGRAM_VARIANTS)
        assert all(v == [] for v in results.values())

    def test_programs_cached_by_fit_epochs_pass(self):
        """The checker over a cache populated by a REAL training run —
        the tier-1 wiring, not a hand-built key set."""
        net = _ff_net()
        data = _ff_data()
        net.fit_epochs(ListDataSetIterator(data, batch_size=16), 2)
        cache = net.build_epoch_cache(
            ListDataSetIterator(data, batch_size=16))
        assert net._epoch_steps  # fit_epochs populated the cache
        check_network_contracts(net, cache)

    def test_seeded_callback_in_program_fails(self):
        """Seeded violation: a host callback compiled into the fused
        program must fail the contract check."""
        net, cache = _net_and_cache("ff")
        key = (True, 1, False, 0)
        run = net._epoch_run_fn(*key)

        def bad(params, upd, nst, it0, lr, xs, ys, fms, lms, keys):
            p, u, s, hist = run(params, upd, nst, it0, lr, xs, ys, fms,
                                lms, keys)
            echoed = jax.pure_callback(
                lambda h: h,
                jax.ShapeDtypeStruct(hist.shape, hist.dtype), hist)
            return p, u, s, hist + 0 * echoed

        net._epoch_steps[key] = jax.jit(bad, donate_argnums=(0, 1, 2))
        with pytest.raises(ContractViolation) as exc:
            check_network_contracts(net, cache)
        assert "pure_callback" in str(exc.value)
        assert str(key) in str(exc.value)

    def test_seeded_dropped_donation_fails(self):
        """Seeded violation: the same program jitted WITHOUT
        donate_argnums — every training-state leaf loses its alias."""
        net, cache = _net_and_cache("ff")
        key = (True, 1, False, 0)
        net._epoch_steps[key] = jax.jit(net._epoch_run_fn(*key))
        with pytest.raises(ContractViolation) as exc:
            check_network_contracts(net, cache)
        assert "input-output alias" in str(exc.value)

    def test_seeded_key_output_mismatch_fails(self):
        """Seeded violation: a guarded program cached under an unguarded
        key — the output arity no longer matches the key's contract."""
        net, cache = _net_and_cache("ff")
        net._epoch_steps[(True, 1, False, 0)] = jax.jit(
            net._epoch_run_fn(True, 1, True, 0),
            donate_argnums=(0, 1, 2))
        with pytest.raises(ContractViolation) as exc:
            check_network_contracts(net, cache)
        assert "outputs" in str(exc.value)

    def test_violations_collected_without_raise(self):
        net, cache = _net_and_cache("ff")
        key = (True, 1, False, 0)
        net._epoch_steps[key] = jax.jit(net._epoch_run_fn(*key))
        results = check_network_contracts(net, cache,
                                          raise_on_violation=False)
        assert results[key] and "alias" in results[key][0]

    def test_empty_program_cache_is_an_error_not_a_pass(self):
        """A vacuous check must never look like a passed one: an empty
        (or renamed-away) _epoch_steps cache raises unless the caller
        explicitly opts into emptiness."""
        net, cache = _net_and_cache("ff")
        net._epoch_steps.clear()
        with pytest.raises(ValueError, match="no cached fused programs"):
            check_network_contracts(net, cache)
        assert check_network_contracts(
            net, cache, require_programs=False) == {}

    def test_specs_match_real_program_signature(self):
        """fused_program_specs must stay in lockstep with the
        _epoch_run_fn signature: eval_shape on the REAL program with the
        generated specs succeeds and yields the documented histories."""
        net, cache = _net_and_cache("rnn")
        specs = fused_program_specs(net, cache, epochs=3)
        out = jax.eval_shape(net._epoch_train_step(True, 1, True, 1),
                             *specs)
        assert len(out) == 6  # state x3 + losses + trips + metrics
        assert tuple(out[3].shape) == (3, cache.n_batches)
        assert tuple(out[5].shape) == (3, cache.n_batches, 4)


class TestContractPrimitives:
    def test_callback_primitives_detected(self):
        def f(x):
            return jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x)

        jaxpr = jax.make_jaxpr(f)(jnp.float32(0))
        assert callback_primitives(jaxpr) == ["pure_callback"]

    def test_clean_program_has_no_callbacks(self):
        jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2)(jnp.float32(0))
        assert callback_primitives(jaxpr) == []

    def test_collective_axes_sees_through_pmap(self):
        n = jax.local_device_count()
        f = jax.pmap(lambda x: jax.lax.psum(x, "batch"),
                     axis_name="batch")
        jaxpr = jax.make_jaxpr(f)(jnp.zeros((n, 2), jnp.float32))
        axes = collective_axes(jaxpr)
        assert "batch" in axes
        assert "psum" in axes["batch"]

    def test_callbacks_found_inside_scan(self):
        def body(c, x):
            y = jax.pure_callback(
                lambda v: v, jax.ShapeDtypeStruct((), jnp.float32), x)
            return c + y, y

        def f(xs):
            return jax.lax.scan(body, jnp.float32(0), xs)

        jaxpr = jax.make_jaxpr(f)(jnp.zeros((4,), jnp.float32))
        assert callback_primitives(jaxpr) == ["pure_callback"]

    def test_donated_arg_indices_parse_lowered_text(self):
        f = jax.jit(lambda a, b: (a + 1.0, b), donate_argnums=(0,))
        text = f.lower(jnp.zeros((2,), jnp.float32),
                       jnp.zeros((2,), jnp.float32)).as_text()
        donated = donated_arg_indices(text)
        assert 0 in donated
        assert 1 not in donated

    def test_donated_arg_indices_survive_sharding_attrs(self):
        # SPMD programs interleave mhlo.sharding attrs — whose values
        # contain nested braces AND commas inside the quoted string —
        # with the donor markers; the parser must not lose the marker
        sig = (
            'func.func public @main('
            '%arg0: tensor<8x4xf32> {mhlo.sharding = '
            '"{devices=[8,1]<=[8]}", tf.aliasing_output = 0 : i32}, '
            '%arg1: tensor<8x4xf32> {mhlo.sharding = '
            '"{replicated}"}, '
            '%arg2: tensor<4xf32> {jax.buffer_donor = true, '
            'mhlo.sharding = "{devices=[8,1]<=[8]}"}'
            ') -> (tensor<8x4xf32>)')
        assert donated_arg_indices(sig) == [0, 2]

    def test_donated_arg_indices_on_real_sharded_program(self):
        mesh = jax.sharding.Mesh(np.array(jax.devices()), ("data",))
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data"))
        f = jax.jit(lambda a, b: (a + 1.0, b), donate_argnums=(0,),
                    in_shardings=(sh, sh), out_shardings=(sh, sh))
        z = jnp.zeros((jax.device_count() * 2,), jnp.float32)
        donated = donated_arg_indices(f.lower(z, z).as_text())
        assert 0 in donated
        assert 1 not in donated
