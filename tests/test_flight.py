# dl4j-lint: skip-file -- rule-fixture corpus: snippet strings in this file are seeded violations and would (correctly) trip the rules they test
"""Run-level observability tests (PR 9): the RunLedger goodput/badput
classification, the crash-surviving flight recorder, the postmortem
end-state classifier, the fleet heartbeat telemetry, and the
chunk-boundary-only lint contract.

The contracts that matter most:

1. The ledger + flight recorder are OBSERVATIONAL: trained params with
   the recorder live are bitwise-identical to off (FF/RNN/graph + the
   SPMD wrapper).
2. Crash forensics: a fused-run subprocess killed -9 mid-chunk leaves
   segments from which ``flight_report`` reconstructs the timeline and
   classifies the death as ``crashed``; the BENCH_r04/r05 wedged-grant
   shape classifies as ``wedged``.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from deeplearning4j_tpu.analysis.engine import LintConfig, run_lint
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.monitor import (
    SpanTracer,
    metrics,
    set_tracer,
    telemetry_summary,
    tracer,
)
from deeplearning4j_tpu.monitor.exporters import JsonlExporter
from deeplearning4j_tpu.monitor.flight import (
    FlightRecorder,
    classify_end_state,
    flight_record,
    load_flight_records,
    set_flight,
    shift_rotate,
)
from deeplearning4j_tpu.monitor.ledger import (
    RunLedger,
    run_ledger,
    set_run_ledger,
)
from deeplearning4j_tpu.monitor.trace import Span
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.parallel.cluster import HeartbeatMonitor
from deeplearning4j_tpu.parallel.statetracker import (
    FileStateTracker,
    InMemoryStateTracker,
    StateTracker,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FLIGHT_REPORT = os.path.join(REPO, "scripts", "flight_report.py")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


flight_report = _load_script("flight_report")
bench_report = _load_script("bench_report")


@pytest.fixture(autouse=True)
def _fresh_global_telemetry():
    """Fresh registry/tracer/ledger and NO flight recorder per test."""
    metrics().reset()
    set_tracer(SpanTracer())
    set_run_ledger(RunLedger())
    set_flight(None)
    yield
    metrics().reset()
    set_tracer(None)
    set_run_ledger(None)
    set_flight(None)


# ---------------------------------------------------------------------------
# model/data helpers (the test_telemetry shapes)
# ---------------------------------------------------------------------------


def _ff_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _rnn_net(seed=0):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.02)
        .updater(Updater.SGD).list()
        .layer(0, L.GravesLSTM(n_in=3, n_out=6, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=6, n_out=4,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _ff_graph(seed=0):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=12,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=12, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build())


def _ff_data(n=48, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _rnn_data(n=24, t=5, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, t))]
    return DataSet(x, y)


def _leaves_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        (np.asarray(x) == np.asarray(y)).all() for x, y in zip(la, lb))


def _span(name, start, end, **attrs):
    sp = Span(name, 0, None, start, attrs)
    sp.end_s = end
    return sp


def _event(name, at, **attrs):
    return _span(name, at, at, **attrs)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# RunLedger: the wall-time classification
# ---------------------------------------------------------------------------


class TestRunLedger:
    def test_classification_priorities_and_goodput(self):
        """The worked example: a 25 s window with one run, blocking and
        background badput, and every priority rule exercised."""
        clock = FakeClock(0.0)
        spans = [
            _span("checkpoint.write", 2, 3),             # foreground
            _span("cache.build", 5, 10),
            _span("retry.sleep", 12, 13),                 # inside run
            _span("checkpoint.write", 14, 18, background=True),  # hidden
            _event("watchdog.stall", 16, stalled_s=2.0),  # covers 14-16
        ]
        ledger = RunLedger(clock=clock, span_source=lambda: spans)
        clock.t = 10.0
        ledger.run_start(model="X", epochs=2)
        clock.t = 20.0
        ledger.run_end(status="clean")
        clock.t = 25.0
        rep = ledger.report()
        st = rep["states"]
        assert st["checkpoint"] == pytest.approx(1.0)
        assert st["cache_build"] == pytest.approx(5.0)
        assert st["retry_backoff"] == pytest.approx(1.0)
        assert st["watchdog_stall"] == pytest.approx(2.0)
        # compute = run window minus the retry second and the stall pair
        assert st["compute"] == pytest.approx(7.0)
        assert st["idle"] == pytest.approx(9.0)
        # goodput excludes idle: 7 / (25 - 9)
        assert rep["goodput_pct"] == pytest.approx(100 * 7 / 16, abs=0.01)
        # the background write never became badput, but is visible
        assert rep["hidden_checkpoint_s"] == pytest.approx(4.0)
        assert rep["badput"] == {"checkpoint": 1.0, "cache_build": 5.0,
                                 "retry_backoff": 1.0,
                                 "watchdog_stall": 2.0}

    def test_per_run_report_cached_at_run_end(self):
        clock = FakeClock(0.0)
        spans = [_span("retry.sleep", 12, 13)]
        ledger = RunLedger(clock=clock, span_source=lambda: spans)
        clock.t = 10.0
        ledger.run_start(model="MLN", epochs=3)
        for _ in range(3):
            ledger.chunk_start()
            clock.t += 2.0
            ledger.chunk_done()
        rep = ledger.run_end(status="clean")
        # within [10, 16]: 1 s retry, 5 s compute
        assert rep["goodput_pct"] == pytest.approx(100 * 5 / 6, abs=0.01)
        assert ledger.last_run_goodput() == rep["goodput_pct"]
        run = ledger.report()["runs"][0]
        assert run["chunks"] == 3
        assert run["status"] == "clean"
        assert run["wall_s"] == pytest.approx(6.0)
        assert run["host_dispatch_s"] == pytest.approx(6.0)
        assert run["model"] == "MLN"

    def test_grant_wait_outranks_everything(self):
        clock = FakeClock(0.0)
        spans = [
            _span("grant.acquire", 0, 8),
            _span("cache.build", 4, 6),  # overlapped: grant wins
        ]
        ledger = RunLedger(clock=clock, span_source=lambda: spans)
        clock.t = 8.0
        st = ledger.report()["states"]
        assert st["grant_wait"] == pytest.approx(8.0)
        assert st["cache_build"] == 0.0

    def test_active_run_counts_up_to_now(self):
        clock = FakeClock(0.0)
        ledger = RunLedger(clock=clock, span_source=lambda: [])
        ledger.run_start(model="X", epochs=1)
        clock.t = 4.0
        rep = ledger.report()
        assert rep["run_in_flight"] is True
        assert rep["states"]["compute"] == pytest.approx(4.0)
        assert rep["goodput_pct"] == pytest.approx(100.0)

    def test_drive_epoch_chunks_populates_ledger(self):
        net = _ff_net()
        net.fit_epochs(ListDataSetIterator(_ff_data(), 12), 3,
                       chunk_epochs=1)
        rep = run_ledger().report()
        assert rep["n_runs"] == 1
        run = rep["runs"][0]
        assert run["status"] == "clean"
        assert run["chunks"] == 3
        assert run["model"] == "MultiLayerNetwork"
        assert run["goodput_pct"] is not None and run["goodput_pct"] > 0

    def test_telemetry_summary_embeds_ledger_block(self):
        net = _ff_net()
        net.fit_epochs(ListDataSetIterator(_ff_data(), 12), 2,
                       chunk_epochs=1)
        block = telemetry_summary()["ledger"]
        assert block["n_runs"] == 1
        assert set(block["states"]) >= {"compute", "idle", "grant_wait"}
        json.dumps(block)  # artifact-embeddable

    def test_diverged_run_closes_with_error_status(self):
        from deeplearning4j_tpu.resilience.guard import (
            TrainingDivergedError)

        net = _ff_net()
        data = _ff_data()
        data.features = np.asarray(data.features)
        data.features[3, :] = np.nan
        with pytest.raises(TrainingDivergedError):
            net.fit_epochs(ListDataSetIterator(data, 12), 2,
                           chunk_epochs=1, guard="raise")
        runs = run_ledger().report()["runs"]
        assert runs and runs[-1]["status"].startswith("error:")


# ---------------------------------------------------------------------------
# FlightRecorder: the on-disk ring
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_records_round_trip_and_heartbeats(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), heartbeat_s_=0.05)
        rec.record("run.start", model="X", epochs=3)
        rec.record("chunk.done", epoch0=0)
        assert rec.flush()
        time.sleep(0.12)  # at least one heartbeat lands
        rec.close()
        records = load_flight_records(str(tmp_path))
        kinds = [r["kind"] for r in records]
        assert kinds[0] == "run.start"
        assert "chunk.done" in kinds
        assert "flight.heartbeat" in kinds
        assert kinds[-1] == "flight.close"
        assert all("t_wall" in r for r in records)
        hb = next(r for r in records if r["kind"] == "flight.heartbeat")
        assert hb["interval_s"] == pytest.approx(0.05)

    def test_heartbeat_carries_counter_deltas(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), heartbeat_s_=0.05)
        metrics().counter("flight_test_total").inc(3)
        time.sleep(0.12)
        rec.close()
        beats = [r for r in load_flight_records(str(tmp_path))
                 if r["kind"] == "flight.heartbeat" and "counters" in r]
        assert beats and beats[0]["counters"]["flight_test_total"] == 3.0

    def test_segment_rotation_bounds_disk(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), segment_bytes_=300,
                             max_segments_=3, heartbeat_s_=60)
        for i in range(200):
            rec.record("chunk.done", epoch0=i, pad="x" * 40)
        rec.flush()
        rec.close()
        files = sorted(p for p in os.listdir(tmp_path)
                       if p.startswith("flight-"))
        assert rec.segments_rotated > 0
        assert len(files) <= 3
        total = sum(os.path.getsize(tmp_path / p) for p in files)
        # the cap: segments x segment size (+ one in-flight record)
        assert total <= 3 * 300 + 200
        # the ring keeps the NEWEST records: the close marker survives
        records = load_flight_records(str(tmp_path))
        assert records[-1]["kind"] == "flight.close"
        assert records[-2]["epoch0"] == 199

    def test_fresh_recorder_opens_new_segment(self, tmp_path):
        rec1 = FlightRecorder(str(tmp_path))
        rec1.record("run.start")
        rec1.close()
        rec2 = FlightRecorder(str(tmp_path))
        rec2.record("run.start")
        rec2.close()
        segs = {r["_segment"] for r in load_flight_records(str(tmp_path))}
        assert len(segs) == 2  # never appends to a possibly-torn segment

    def test_torn_tail_is_skipped_not_fatal(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.record("run.start", model="X")
        rec.flush()
        rec.close()
        # simulate the write the crash interrupted
        path = tmp_path / sorted(os.listdir(tmp_path))[-1]
        with open(path, "a") as f:
            f.write('{"kind": "chunk.done", "epo')
        records = load_flight_records(str(tmp_path))
        assert [r["kind"] for r in records
                if r["kind"] != "flight.heartbeat"] == ["run.start",
                                                        "flight.close"]

    def test_record_never_raises_after_close(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        rec.close()
        rec.record("chunk.done")  # no-op, no error

    def test_tracer_spans_forward_into_flight(self, tmp_path):
        rec = FlightRecorder(str(tmp_path))
        set_flight(rec)
        try:
            with tracer().span("cache.build", kind="T"):
                pass
        finally:
            set_flight(None)
        rec.flush()
        rec.close()
        spans = [r for r in load_flight_records(str(tmp_path))
                 if r["kind"] == "span"]
        assert spans and spans[0]["name"] == "cache.build"
        assert spans[0]["attrs"]["kind"] == "T"


class TestJsonlExporterBound:
    def test_rotation_caps_disk_use(self, tmp_path):
        """The PR-6 unbounded-append hole: the exporter now rotates at
        max_bytes through the shared shift mechanism."""
        path = str(tmp_path / "telemetry.jsonl")
        exp = JsonlExporter(path, max_bytes=500, backups=2)
        for i in range(100):
            exp.write({"kind": "span", "i": i, "pad": "y" * 30})
        files = sorted(os.listdir(tmp_path))
        assert "telemetry.jsonl" in files
        assert "telemetry.jsonl.1" in files
        assert len(files) <= 3  # live + 2 backups, never more
        assert all(os.path.getsize(tmp_path / f) <= 500 + 60
                   for f in files)
        # newest record is in the live file
        with open(path) as f:
            last = json.loads(f.readlines()[-1])
        assert last["i"] == 99

    def test_survives_external_deletion(self, tmp_path):
        """Operator cleanup (or a foreign logrotate) unlinking the live
        file must not wedge the exporter: the next write recreates it."""
        path = str(tmp_path / "telemetry.jsonl")
        exp = JsonlExporter(path, max_bytes=200, backups=1)
        for i in range(10):
            exp.write({"i": i, "pad": "x" * 40})
        os.unlink(path)  # _size is still near the threshold
        for i in range(10, 20):
            exp.write({"i": i, "pad": "x" * 40})
        with open(path) as f:
            lines = [json.loads(ln) for ln in f]
        assert lines and lines[-1]["i"] == 19

    def test_unbounded_opt_out(self, tmp_path):
        path = str(tmp_path / "t.jsonl")
        exp = JsonlExporter(path, max_bytes=0)
        for i in range(50):
            exp.write({"i": i, "pad": "z" * 100})
        assert os.listdir(tmp_path) == ["t.jsonl"]

    def test_shift_rotate_shifts_and_caps(self, tmp_path):
        path = str(tmp_path / "f")
        for content in ("one", "two", "three", "four"):
            with open(path, "w") as f:
                f.write(content)
            shift_rotate(path, backups=2)
            assert not os.path.exists(path)
        assert open(path + ".1").read() == "four"
        assert open(path + ".2").read() == "three"
        assert not os.path.exists(path + ".3")


# ---------------------------------------------------------------------------
# end-state classification (the postmortem verdicts)
# ---------------------------------------------------------------------------


def _write_segment(directory, records, index=1):
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory,
                           f"flight-{index:08d}.jsonl"), "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


class TestEndStateClassification:
    def test_clean_run(self):
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t, "model": "MLN"},
            {"kind": "chunk.done", "t_wall": t + 1},
            {"kind": "run.end", "t_wall": t + 2, "status": "clean"},
            {"kind": "flight.close", "t_wall": t + 3},
        ]
        assert classify_end_state(records)["end_state"] == "clean"

    def test_preempted_run(self):
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t},
            {"kind": "span", "name": "preemption.latch", "t_wall": t + 1},
            {"kind": "run.end", "t_wall": t + 2, "status": "stopped"},
        ]
        assert classify_end_state(records)["end_state"] == "preempted"

    def test_user_early_stop_without_latch_is_clean(self):
        """status 'stopped' is set by ANY on_chunk callback returning
        True (e.g. a convergence early-stop) — only the preemption
        latch on the timeline makes it a preemption."""
        records = [
            {"kind": "run.start", "t_wall": 1.0},
            {"kind": "run.end", "t_wall": 2.0, "status": "stopped"},
        ]
        out = classify_end_state(records)
        assert out["end_state"] == "clean"
        assert out["status"] == "stopped"

    def test_in_process_error_is_crashed(self):
        records = [
            {"kind": "run.start", "t_wall": 1.0},
            {"kind": "run.end", "t_wall": 2.0,
             "status": "error:TrainingDivergedError"},
        ]
        out = classify_end_state(records)
        assert out["end_state"] == "crashed"
        assert out["status"] == "error:TrainingDivergedError"

    def test_wedged_grant_replays_bench_r04_r05_shape(self):
        """The committed BENCH_r04/r05 wedge: grant acquisition blocks
        for hours BEFORE any run starts (bench wedges in
        _await_backend, pre-sections) — the open grant.wait marker plus
        writer heartbeats marching on with no progress is the wedge
        signature, with no run.start anywhere on the timeline. (r04:
        300 s of silence at heartbeat 1 s; r05: 90 s.)"""
        for silent_s in (300.0, 90.0):
            t = 1000.0
            records = [
                {"kind": "grant.wait", "phase": "acquire",
                 "timeout_s": silent_s, "t_wall": t},
            ] + [
                {"kind": "flight.heartbeat", "t_wall": t + i,
                 "interval_s": 1.0}
                for i in range(1, int(silent_s))
            ]
            out = classify_end_state(records)
            assert out["end_state"] == "wedged"
            assert out["evidence"]["silent_s"] >= 3.0
            assert out["evidence"]["last_progress"]["kind"] == "grant.wait"

    def test_open_grant_marker_is_wedge_even_without_silence(self):
        """The marker is written immediately before a call that can
        block forever: a timeline ENDING on it (even with few surviving
        heartbeats) reads wedged, as docs/observability.md promises."""
        records = [
            {"kind": "grant.wait", "phase": "probe", "t_wall": 1000.0},
            {"kind": "flight.heartbeat", "t_wall": 1000.5,
             "interval_s": 1.0},
        ]
        assert classify_end_state(records)["end_state"] == "wedged"

    def test_mid_run_silence_is_wedged_too(self):
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t},
            {"kind": "chunk.launch", "t_wall": t + 1},
        ] + [
            {"kind": "flight.heartbeat", "t_wall": t + 1 + i,
             "interval_s": 1.0} for i in range(1, 60)
        ]
        out = classify_end_state(records)
        assert out["end_state"] == "wedged"
        assert out["evidence"]["open_run"]["kind"] == "run.start"

    def test_wedge_evidence_event_wins_without_silence(self):
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t},
            {"kind": "chunk.launch", "t_wall": t + 1},
            {"kind": "span", "name": "watchdog.stall", "t_wall": t + 1.5,
             "attrs": {"stalled_s": 120.0}},
        ]
        assert classify_end_state(records)["end_state"] == "wedged"

    def test_abrupt_stop_is_crashed(self):
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t},
            {"kind": "flight.heartbeat", "t_wall": t + 0.5,
             "interval_s": 1.0},
            {"kind": "chunk.launch", "t_wall": t + 1},
        ]
        assert classify_end_state(records)["end_state"] == "crashed"

    def test_drain_evidence_classifies_drained(self):
        """An orderly close whose timeline carries ``serve.drain``
        evidence reads as a planned retirement — and outranks any
        sheds the same storm produced (the shed count stays in the
        evidence)."""
        t = 1000.0
        records = [
            {"kind": "run.start", "t_wall": t},
            {"kind": "serve.shed", "t_wall": t + 0.5, "where": "queue",
             "reason": "deadline", "criticality": "batch"},
            {"kind": "serve.drain", "t_wall": t + 1, "replica": "r1",
             "migrated": 3, "fallback_failovers": 0},
            {"kind": "run.end", "t_wall": t + 2, "status": "clean"},
            {"kind": "flight.close", "t_wall": t + 3},
        ]
        out = classify_end_state(records)
        assert out["end_state"] == "drained"
        assert out["evidence"]["n_drains"] == 1
        assert out["evidence"]["n_sheds"] == 1

    def test_shed_evidence_classifies_shed_overload(self):
        records = [
            {"kind": "run.start", "t_wall": 1000.0},
            {"kind": "serve.shed", "t_wall": 1001.0, "where": "queue",
             "reason": "deadline", "criticality": "best_effort"},
            {"kind": "run.end", "t_wall": 1002.0, "status": "clean"},
            {"kind": "flight.close", "t_wall": 1003.0},
        ]
        out = classify_end_state(records)
        assert out["end_state"] == "shed-overload"
        assert out["evidence"]["n_sheds"] == 1

    def test_no_records(self):
        assert classify_end_state([])["end_state"] == "unknown"


# ---------------------------------------------------------------------------
# bitwise parity: the recorder+ledger observe, never perturb
# ---------------------------------------------------------------------------


class TestFlightBitwiseParity:
    @pytest.mark.parametrize("make_net,make_data", [
        (_ff_net, _ff_data),
        (_rnn_net, _rnn_data),
        (_ff_graph, _ff_data),
    ], ids=["ff", "rnn", "graph"])
    def test_on_vs_off_params_bitwise(self, tmp_path, make_net,
                                      make_data, monkeypatch):
        data = make_data()
        off = make_net()
        h_off = off.fit_epochs(ListDataSetIterator(data, 12), 3,
                               chunk_epochs=1)
        rec = FlightRecorder(str(tmp_path), heartbeat_s_=10.0)
        set_flight(rec)
        monkeypatch.setenv("DL4J_FLIGHT", str(tmp_path))
        try:
            on = make_net()
            h_on = on.fit_epochs(ListDataSetIterator(data, 12), 3,
                                 chunk_epochs=1)
        finally:
            set_flight(None)
        rec.flush()
        rec.close()
        assert _leaves_equal(off.params, on.params)
        assert _leaves_equal(off.updater_state, on.updater_state)
        assert (np.asarray(h_off) == np.asarray(h_on)).all()
        kinds = [r["kind"] for r in load_flight_records(str(tmp_path))]
        assert kinds.count("run.start") == 1
        assert kinds.count("chunk.done") == 3
        assert kinds.count("run.end") == 1

    def test_spmd_wrapper_bitwise(self, tmp_path):
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs the forced multi-device host platform")
        from deeplearning4j_tpu.parallel import ParallelWrapper, build_mesh

        data = _ff_data()

        def run(recorded):
            net = _ff_net()
            wrapper = ParallelWrapper(net, mesh=build_mesh())
            cache = wrapper.build_epoch_cache(
                ListDataSetIterator(data, 12))
            assert cache is not None
            rec = None
            if recorded:
                rec = FlightRecorder(str(tmp_path), heartbeat_s_=10.0)
                set_flight(rec)
            try:
                wrapper.fit_epochs(cache, 3, chunk_epochs=1)
            finally:
                if rec is not None:
                    set_flight(None)
                    rec.close()
            return net

        off = run(False)
        on = run(True)
        assert _leaves_equal(off.params, on.params)
        assert _leaves_equal(off.updater_state, on.updater_state)


# ---------------------------------------------------------------------------
# fleet heartbeat telemetry
# ---------------------------------------------------------------------------


class TestHeartbeatPayloads:
    def test_in_memory_tracker_payload_and_compat(self):
        t = InMemoryStateTracker()
        t.heartbeat("bare")
        t.heartbeat("rich", metrics={"step_s": 0.5, "last_loss": 1.25})
        assert t.heartbeat_metrics("bare") is None
        assert t.heartbeat_metrics("rich") == {"step_s": 0.5,
                                               "last_loss": 1.25}
        assert t.heartbeat_metrics("unknown") is None
        # a payload-less beat REPLACES the payload (newest-beat
        # contract, same as the file backend) — a worker whose
        # payload_fn died must not feed stale step times to fleet_tick
        t.heartbeat("rich")
        assert t.heartbeat_metrics("rich") is None
        t.heartbeat("rich", metrics={"step_s": 0.7})
        t.evict_stale(timeout_s=0.0)
        assert t.heartbeat_metrics("rich") is None  # evicted with beat

    def test_file_tracker_payload_and_legacy_format(self, tmp_path):
        t = FileStateTracker(str(tmp_path))
        t.heartbeat("bare")
        t.heartbeat("rich", metrics={"step_s": 1.5})
        assert t.last_heartbeat("bare") is not None
        assert t.heartbeat_metrics("bare") is None
        assert t.last_heartbeat("rich") is not None
        assert t.heartbeat_metrics("rich") == {"step_s": 1.5}
        # a bare-float beat file from an old worker still parses
        with open(os.path.join(str(tmp_path), "beats", "legacy"),
                  "w") as f:
            f.write("123.5")
        assert t.last_heartbeat("legacy") == 123.5
        assert t.heartbeat_metrics("legacy") is None
        # a torn beat is absent, not an exception
        with open(os.path.join(str(tmp_path), "beats", "torn"),
                  "w") as f:
            f.write('{"t": 12')
        assert t.last_heartbeat("torn") is None

    def test_monitor_posts_payload(self):
        t = InMemoryStateTracker()
        mon = HeartbeatMonitor(t, "w0", interval_s=30.0,
                               payload_fn=lambda: {"step_s": 2.0})
        mon.start()  # first beat posts synchronously
        mon.stop()
        assert t.heartbeat_metrics("w0") == {"step_s": 2.0}

    def test_failing_payload_fn_degrades_to_bare_beat(self):
        t = InMemoryStateTracker()

        def boom():
            raise RuntimeError("telemetry must not block liveness")

        mon = HeartbeatMonitor(t, "w0", interval_s=30.0, payload_fn=boom)
        mon.start()
        mon.stop()
        assert t.last_heartbeat("w0") is not None
        assert t.heartbeat_metrics("w0") is None

    def test_legacy_tracker_without_metrics_kwarg(self):
        class LegacyTracker(StateTracker):
            def __init__(self):
                self.beats = []

            def heartbeat(self, worker_id):  # pre-payload signature
                self.beats.append(worker_id)

        t = LegacyTracker()
        mon = HeartbeatMonitor(t, "w0", interval_s=30.0,
                               payload_fn=lambda: {"step_s": 1.0})
        mon.start()
        mon.stop()
        assert t.beats == ["w0"]  # fell back, still beat


class TestFleetView:
    def _trainer(self, tracker, **kw):
        from deeplearning4j_tpu.parallel.workrouter import (
            DistributedTrainer, IterativeReduceWorkRouter)

        return DistributedTrainer(
            tracker, IterativeReduceWorkRouter(tracker),
            performer_factory=lambda: None, num_workers=3, **kw)

    def test_fleet_tick_gauges_and_straggler_flag(self):
        t = InMemoryStateTracker()
        trainer = self._trainer(t, straggler_ratio=3.0)
        t.heartbeat("w0", metrics={"step_s": 1.0, "goodput_pct": 90.0})
        t.heartbeat("w1", metrics={"step_s": 1.2, "last_loss": 0.5})
        t.heartbeat("w2", metrics={"step_s": 10.0})
        fleet = trainer.fleet_tick()
        assert set(fleet) == {"w0", "w1", "w2"}
        reg = metrics()
        assert reg.gauge("fleet_worker_step_seconds").value(
            worker="w2") == 10.0
        assert reg.gauge("fleet_worker_goodput_pct").value(
            worker="w0") == 90.0
        assert reg.gauge("fleet_worker_last_loss").value(
            worker="w1") == 0.5
        # w2 is 10x the median (1.2): flagged with evidence
        assert trainer.stragglers == {"w2"}
        assert reg.counter("fleet_stragglers_total").value(
            worker="w2") == 1.0
        assert reg.gauge("fleet_stragglers").value() == 1.0
        ev = [s for s in tracer().spans() if s.name == "fleet.straggler"]
        assert ev and ev[0].attrs["worker"] == "w2"
        assert ev[0].attrs["median_s"] == pytest.approx(1.2)
        # recovery un-flags (no repeat counter bump)
        t.heartbeat("w2", metrics={"step_s": 1.1})
        trainer.fleet_tick()
        assert trainer.stragglers == set()
        assert reg.counter("fleet_stragglers_total").value(
            worker="w2") == 1.0

    def test_no_straggler_flag_below_three_workers(self):
        t = InMemoryStateTracker()
        trainer = self._trainer(t)
        t.heartbeat("w0", metrics={"step_s": 1.0})
        t.heartbeat("w1", metrics={"step_s": 100.0})
        trainer.fleet_tick()
        assert trainer.stragglers == set()

    def test_eviction_decision_carries_evidence(self):
        t = InMemoryStateTracker()
        trainer = self._trainer(t, eviction_timeout_s=10.0)
        t.heartbeat("dead", metrics={"step_s": 4.0, "last_loss": 2.5})
        t._beats["dead"] -= 60.0  # silent for a minute
        t.heartbeat("alive", metrics={"step_s": 1.0})
        stale = trainer._evict_tick()
        assert stale == ["dead"]
        assert len(trainer.eviction_log) == 1
        decision = trainer.eviction_log[0]
        assert decision["worker"] == "dead"
        assert decision["timeout_s"] == 10.0
        assert decision["silent_s"] >= 60.0
        assert decision["last_metrics"]["last_loss"] == 2.5
        assert metrics().counter("fleet_evictions_total").value(
            worker="dead") == 1.0
        ev = [s for s in tracer().spans() if s.name == "fleet.evict"]
        assert ev and ev[0].attrs["worker"] == "dead"
        # the live worker kept its beat
        assert t.last_heartbeat("alive") is not None

    def test_end_to_end_fleet_payloads_through_training(self):
        """Workers in a real DistributedTrainer run post step-time
        payloads; the master tick aggregates them into gauges."""
        from deeplearning4j_tpu.parallel.workrouter import (
            DistributedTrainer, HogwildWorkRouter, WorkerPerformer)

        class TinyPerformer(WorkerPerformer):
            def perform(self, payload):
                # slow enough that payload-carrying beats (every 50 ms)
                # land while jobs are still flowing
                time.sleep(0.15)
                return np.ones(4, np.float32) * payload

        t = InMemoryStateTracker()
        for i in range(6):
            t.add_job(float(i))
        trainer = DistributedTrainer(
            t, HogwildWorkRouter(t),
            performer_factory=TinyPerformer, num_workers=2,
            heartbeat_interval_s=0.05)
        trainer.train(timeout_s=30.0)
        fleet = trainer.fleet_tick()
        assert fleet  # at least one worker reported a payload
        some = next(iter(fleet.values()))
        assert some["step_s"] > 0
        assert some["jobs"] >= 1
        # the in-loop (throttled) tick also ran and set the fleet gauge
        assert metrics().gauge("fleet_workers").value() >= 1.0


# ---------------------------------------------------------------------------
# crash forensics: the kill -9 chaos case
# ---------------------------------------------------------------------------

_CHAOS_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=12, n_out=3)).build())
net = MultiLayerNetwork(conf).init()
rng = np.random.default_rng(0)
x = rng.normal(size=(48, 6)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 48)]
# far more epochs than the parent lets us live: it SIGKILLs mid-chunk
net.fit_epochs(ListDataSetIterator(DataSet(x, y), 12), 10 ** 6,
               chunk_epochs=1)
"""


@pytest.mark.chaos
class TestCrashForensics:
    def test_kill9_mid_chunk_classifies_crashed(self, tmp_path):
        """The acceptance case: a REAL fused-run subprocess with
        DL4J_FLIGHT on is kill -9'd mid-chunk; flight_report must
        reconstruct the run/chunk timeline from the surviving segments
        and classify the end state as crashed."""
        flight_dir = str(tmp_path / "flight")
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   DL4J_FLIGHT=flight_dir,
                   DL4J_FLIGHT_HEARTBEAT_S="0.1")
        env.pop("DL4J_FAULTS", None)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHAOS_CHILD.format(repo=REPO)],
            env=env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        try:
            deadline = time.monotonic() + 120.0
            chunks = 0
            while time.monotonic() < deadline:
                chunks = sum(
                    1 for r in load_flight_records(flight_dir)
                    if r.get("kind") == "chunk.done")
                if chunks >= 3:
                    break
                assert proc.poll() is None, \
                    "fused-run child exited before the kill"
                time.sleep(0.1)
            assert chunks >= 3, "no fused chunks recorded within 120s"
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        # classification from the surviving segments alone
        report = flight_report.build_report(flight_dir)
        assert report["end_state"] == "crashed"
        assert report["n_runs_started"] == 1
        assert report["n_chunks_done"] >= 3
        kinds = {r.get("kind") for r in report["timeline"]}
        assert "chunk.done" in kinds
        # and through the CLI, machine-readably
        proc = subprocess.run(
            [sys.executable, FLIGHT_REPORT, "--json", flight_dir],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        out = json.loads(proc.stdout)
        assert out["end_state"] == "crashed"
        assert out["n_chunks_done"] >= 3


# ---------------------------------------------------------------------------
# ledger/flight lint: chunk-boundary-only by contract
# ---------------------------------------------------------------------------


class TestLedgerFlightLint:
    def _lint(self, tmp_path, source):
        path = tmp_path / "snippet.py"
        path.write_text(textwrap.dedent(source))
        config = LintConfig(root=str(tmp_path),
                            registered_markers={"chaos", "slow"})
        return run_lint(paths=[str(path)],
                        select=["host-sync-in-hot-path"], config=config)

    def test_flight_record_in_traced_function_is_flagged(self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.analysis.annotations import traced
            from deeplearning4j_tpu.monitor.flight import flight_record

            @traced
            def step(x):
                flight_record("step", i=0)
                return x
            """)
        assert len(found) == 1
        assert "flight" in found[0].message
        assert "chunk boundaries" in found[0].message

    def test_ledger_mark_reachable_from_hot_root_is_flagged(
            self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.monitor.ledger import ledger_chunk_done

            def _epoch_run_fn(self, xs):
                return helper(xs)

            def helper(xs):
                ledger_chunk_done(epoch0=0)
                return xs
            """)
        assert len(found) == 1
        assert "ledger" in found[0].message

    def test_chunk_boundary_call_is_clean(self, tmp_path):
        found = self._lint(tmp_path, """
            from deeplearning4j_tpu.monitor.ledger import (
                ledger_chunk_done, ledger_chunk_start)

            def drive_chunks(net):
                # host-side, between dispatches: the permitted site
                ledger_chunk_start(epoch0=0)
                ledger_chunk_done(epoch0=0)
            """)
        assert found == []

    def test_shipped_tree_is_lint_clean(self):
        """The chunk driver + the new monitor modules introduce no
        findings under the extended host-sync rule."""
        config = LintConfig(root=REPO,
                            registered_markers={"chaos", "slow"})
        found = run_lint(
            paths=[os.path.join(REPO, "deeplearning4j_tpu", "perf",
                                "epoch_cache.py"),
                   os.path.join(REPO, "deeplearning4j_tpu", "monitor",
                                "ledger.py"),
                   os.path.join(REPO, "deeplearning4j_tpu", "monitor",
                                "flight.py"),
                   os.path.join(REPO, "deeplearning4j_tpu", "parallel",
                                "workrouter.py")],
            select=None, config=config)
        assert found == [], [f"{f.rule}:{f.path}:{f.line}" for f in found]


# ---------------------------------------------------------------------------
# bench_report: goodput columns + --json
# ---------------------------------------------------------------------------


def _artifact(tmp_path, name, n, value=100.0, goodput=92.5, badput=None):
    row = {
        "n": n, "rc": 0,
        "parsed": {
            "metric": "m", "value": value, "unit": "u",
            "extras": {
                "telemetry": {
                    "metrics": {}, "spans": {},
                    "ledger": {
                        "goodput_pct": goodput,
                        "badput": badput or {"cache_build": 1.5},
                    },
                },
            },
        },
    }
    path = tmp_path / name
    path.write_text(json.dumps(row))
    return str(path)


class TestBenchReportLedgerColumns:
    def test_goodput_column_in_table(self, tmp_path, capsys):
        files = [_artifact(tmp_path, "BENCH_r06.json", 6, goodput=91.0)]
        assert bench_report.main(files) == 0
        out = capsys.readouterr().out
        assert "goodput%" in out
        assert "91" in out
        assert "cache_build=1.5s" in out

    def test_json_mode_machine_readable(self, tmp_path, capsys):
        files = [
            _artifact(tmp_path, "BENCH_r06.json", 6, value=100.0),
            _artifact(tmp_path, "BENCH_r07.json", 7, value=50.0),
        ]
        rc = bench_report.main(["--json", "--check"] + files)
        out = json.loads(capsys.readouterr().out)
        assert rc == 1  # 50% drop gates, json mode included
        assert [r["round"] for r in out["rounds"]] == [6, 7]
        assert out["rounds"][0]["goodput_pct"] == 92.5
        assert out["rounds"][0]["badput"] == {"cache_build": 1.5}
        assert out["regressions"]
        assert "headline:m" in out["series"]

    def test_json_mode_clean_exit(self, tmp_path, capsys):
        files = [_artifact(tmp_path, "BENCH_r06.json", 6)]
        assert bench_report.main(["--json", "--check"] + files) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["regressions"] == []

    def test_pre_ledger_rounds_show_no_goodput(self, tmp_path, capsys):
        committed = os.path.join(REPO, "BENCH_r03.json")
        assert bench_report.main([committed]) == 0
        out = capsys.readouterr().out
        assert "goodput%" in out  # column exists, value is '-'
