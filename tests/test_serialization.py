"""ModelSerializer round-trip tests (checkpoint contract: conf JSON + params
+ updater state; util/ModelSerializer.java parity)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import ModelSerializer


def toy(n=64, d=6, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(c)[rng.integers(0, c, n)].astype(np.float32)
    return DataSet(x, y)


def make_net():
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(1).learning_rate(0.05).updater(Updater.ADAM)
        .list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="relu"))
        .layer(1, L.BatchNormalization())
        .layer(2, L.OutputLayer(n_out=3))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestModelSerializer:
    def test_roundtrip_outputs_identical(self, tmp_path):
        net = make_net()
        ds = toy()
        net.fit(ds)
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)),
            np.asarray(restored.output(ds.features)), rtol=1e-6)
        assert restored.iteration_count == net.iteration_count

    def test_updater_state_resumes_identically(self, tmp_path):
        """Training N+M steps straight == N steps, checkpoint, restore, M
        steps — the updater-state-in-checkpoint contract."""
        ds = toy()
        net_a = make_net()
        for _ in range(5):
            net_a.fit(ds)

        net_b = make_net()
        for _ in range(2):
            net_b.fit(ds)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(net_b, path)
        net_c = ModelSerializer.restore_multi_layer_network(path)
        for _ in range(3):
            net_c.fit(ds)
        np.testing.assert_allclose(
            net_a.get_flat_params(), net_c.get_flat_params(), rtol=1e-4, atol=1e-6)

    def test_without_updater(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path, save_updater=False)
        restored = ModelSerializer.restore_multi_layer_network(path)
        ds = toy(n=8)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)),
            np.asarray(restored.output(ds.features)), rtol=1e-6)

    def test_wrong_type_raises(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path)
        with pytest.raises(TypeError):
            ModelSerializer.restore_computation_graph(path)

    def test_dispatching_restore(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore(path)
        assert isinstance(restored, MultiLayerNetwork)

    def test_graph_roundtrip(self, tmp_path):
        conf = (
            NeuralNetConfiguration.Builder().seed(2).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_layer("b", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("out", L.OutputLayer(n_in=16, n_out=3), "m")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        ds = toy()
        net.fit(ds)
        path = str(tmp_path / "graph.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_computation_graph(path)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)[0]),
            np.asarray(restored.output(ds.features)[0]), rtol=1e-6)

    def test_layer_names_with_slashes(self, tmp_path):
        """'/' in user-chosen vertex names must not collide with the archive
        path delimiter."""
        conf = (
            NeuralNetConfiguration.Builder().seed(5).graph_builder()
            .add_inputs("in")
            .add_layer("enc/dense", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_layer("out", L.OutputLayer(n_in=8, n_out=3), "enc/dense")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        path = str(tmp_path / "slash.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_computation_graph(path)
        x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(net.output(x)[0]), np.asarray(restored.output(x)[0]),
            rtol=1e-6)

    def test_truncated_checkpoint_raises(self, tmp_path):
        import io
        import zipfile

        net = make_net()
        path = str(tmp_path / "trunc.zip")
        ModelSerializer.write_model(net, path)
        # rewrite the archive with a coefficients.npz missing layer "2"
        with zipfile.ZipFile(path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()}
        data = np.load(io.BytesIO(entries["coefficients.npz"]))
        kept = {k: data[k] for k in data.files if not k.startswith("2/")}
        buf = io.BytesIO()
        np.savez(buf, **kept)
        entries["coefficients.npz"] = buf.getvalue()
        with zipfile.ZipFile(path, "w") as zf:
            for n, payload in entries.items():
                zf.writestr(n, payload)
        with pytest.raises(ValueError, match="missing parameter"):
            ModelSerializer.restore_multi_layer_network(path)

    def test_pooling_net_roundtrip(self, tmp_path):
        """Param-less layers (pooling) must survive the npz round-trip."""
        conf = (
            NeuralNetConfiguration.Builder().seed(3).list()
            .layer(0, L.ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
            .layer(1, L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(2, L.OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        path = str(tmp_path / "cnn.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        x = np.random.default_rng(0).normal(size=(2, 8, 8, 1)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(net.output(x)), np.asarray(restored.output(x)), rtol=1e-6)
