"""ModelSerializer round-trip tests (checkpoint contract: conf JSON + params
+ updater state; util/ModelSerializer.java parity)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils import ModelSerializer


def toy(n=64, d=6, c=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.eye(c)[rng.integers(0, c, n)].astype(np.float32)
    return DataSet(x, y)


def make_net():
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(1).learning_rate(0.05).updater(Updater.ADAM)
        .list()
        .layer(0, L.DenseLayer(n_in=6, n_out=12, activation="relu"))
        .layer(1, L.BatchNormalization())
        .layer(2, L.OutputLayer(n_out=3))
        .set_input_type(InputType.feed_forward(6))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestModelSerializer:
    def test_roundtrip_outputs_identical(self, tmp_path):
        net = make_net()
        ds = toy()
        net.fit(ds)
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)),
            np.asarray(restored.output(ds.features)), rtol=1e-6)
        assert restored.iteration_count == net.iteration_count

    def test_updater_state_resumes_identically(self, tmp_path):
        """Training N+M steps straight == N steps, checkpoint, restore, M
        steps — the updater-state-in-checkpoint contract."""
        ds = toy()
        net_a = make_net()
        for _ in range(5):
            net_a.fit(ds)

        net_b = make_net()
        for _ in range(2):
            net_b.fit(ds)
        path = str(tmp_path / "ckpt.zip")
        ModelSerializer.write_model(net_b, path)
        net_c = ModelSerializer.restore_multi_layer_network(path)
        for _ in range(3):
            net_c.fit(ds)
        np.testing.assert_allclose(
            net_a.get_flat_params(), net_c.get_flat_params(), rtol=1e-4, atol=1e-6)

    def test_without_updater(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path, save_updater=False)
        restored = ModelSerializer.restore_multi_layer_network(path)
        ds = toy(n=8)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)),
            np.asarray(restored.output(ds.features)), rtol=1e-6)

    def test_wrong_type_raises(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path)
        with pytest.raises(TypeError):
            ModelSerializer.restore_computation_graph(path)

    def test_dispatching_restore(self, tmp_path):
        net = make_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore(path)
        assert isinstance(restored, MultiLayerNetwork)

    def test_graph_roundtrip(self, tmp_path):
        conf = (
            NeuralNetConfiguration.Builder().seed(2).learning_rate(0.01)
            .graph_builder()
            .add_inputs("in")
            .add_layer("a", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_layer("b", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("out", L.OutputLayer(n_in=16, n_out=3), "m")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        ds = toy()
        net.fit(ds)
        path = str(tmp_path / "graph.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_computation_graph(path)
        np.testing.assert_allclose(
            np.asarray(net.output(ds.features)[0]),
            np.asarray(restored.output(ds.features)[0]), rtol=1e-6)

    def test_layer_names_with_slashes(self, tmp_path):
        """'/' in user-chosen vertex names must not collide with the archive
        path delimiter."""
        conf = (
            NeuralNetConfiguration.Builder().seed(5).graph_builder()
            .add_inputs("in")
            .add_layer("enc/dense", L.DenseLayer(n_in=6, n_out=8), "in")
            .add_layer("out", L.OutputLayer(n_in=8, n_out=3), "enc/dense")
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        path = str(tmp_path / "slash.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_computation_graph(path)
        x = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(net.output(x)[0]), np.asarray(restored.output(x)[0]),
            rtol=1e-6)

    def test_truncated_checkpoint_raises(self, tmp_path):
        import io
        import zipfile

        net = make_net()
        path = str(tmp_path / "trunc.zip")
        ModelSerializer.write_model(net, path)
        # rewrite the archive with a coefficients.npz missing layer "2"
        with zipfile.ZipFile(path) as zf:
            entries = {n: zf.read(n) for n in zf.namelist()}
        data = np.load(io.BytesIO(entries["coefficients.npz"]))
        kept = {k: data[k] for k in data.files if not k.startswith("2/")}
        buf = io.BytesIO()
        np.savez(buf, **kept)
        entries["coefficients.npz"] = buf.getvalue()
        with zipfile.ZipFile(path, "w") as zf:
            for n, payload in entries.items():
                zf.writestr(n, payload)
        with pytest.raises(ValueError, match="missing parameter"):
            ModelSerializer.restore_multi_layer_network(path)

    def test_pooling_net_roundtrip(self, tmp_path):
        """Param-less layers (pooling) must survive the npz round-trip."""
        conf = (
            NeuralNetConfiguration.Builder().seed(3).list()
            .layer(0, L.ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
            .layer(1, L.SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(2, L.OutputLayer(n_out=2))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        path = str(tmp_path / "cnn.zip")
        ModelSerializer.write_model(net, path)
        restored = ModelSerializer.restore_multi_layer_network(path)
        x = np.random.default_rng(0).normal(size=(2, 8, 8, 1)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(net.output(x)), np.asarray(restored.output(x)), rtol=1e-6)


class TestTransformerLMSerialization:
    def test_round_trip_params_opt_state_and_resume(self):
        """write_model/restore for the flagship LM: params, Adam state,
        and step_count round-trip; the restored model produces identical
        logits AND takes an identical next training step (updater state
        is part of the checkpoint contract, SURVEY §5)."""
        import tempfile

        import jax
        import jax.numpy as jnp
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        lm = TransformerLM(vocab_size=32, d_model=32, num_heads=4,
                           num_layers=2, max_len=16, lr=3e-3, seed=3,
                           dtype_policy="bf16", pos_encoding="rope").init()
        tok = np.asarray(
            np.random.default_rng(0).integers(0, 32, (4, 16)), np.int32)
        step = lm.make_train_step(donate=False)
        for _ in range(3):
            lm.fit_batch(tok, train_step=step)

        with tempfile.TemporaryDirectory() as d:
            path = f"{d}/lm.zip"
            ModelSerializer.write_model(lm, path)
            back = ModelSerializer.restore_transformer_lm(path)

        assert back.get_config() == lm.get_config()
        assert back.step_count == 3
        np.testing.assert_array_equal(
            np.asarray(back.forward(back.params, tok), np.float32),
            np.asarray(lm.forward(lm.params, tok), np.float32))
        # one more step from the SAME optimizer state must match exactly
        s2 = lm.make_train_step(donate=False)
        s3 = back.make_train_step(donate=False)
        la = lm.fit_batch(tok, train_step=s2)
        lb = back.fit_batch(tok, train_step=s3)
        assert la == lb
        for a, b in zip(jax.tree_util.tree_leaves(lm.params),
                        jax.tree_util.tree_leaves(back.params)):
            np.testing.assert_array_equal(np.asarray(a, np.float32),
                                          np.asarray(b, np.float32))

    def test_type_dispatch_guard(self):
        import tempfile

        import pytest as _pytest
        from deeplearning4j_tpu.models.transformer import TransformerLM
        from deeplearning4j_tpu.utils.serializer import ModelSerializer

        lm = TransformerLM(vocab_size=16, d_model=32, num_heads=4,
                           num_layers=1, max_len=8, seed=0).init()
        with tempfile.TemporaryDirectory() as d:
            path = f"{d}/lm.zip"
            ModelSerializer.write_model(lm, path)
            with _pytest.raises(TypeError, match="restore_transformer_lm"):
                ModelSerializer.restore_multi_layer_network(path)
            assert ModelSerializer.restore(path).vocab_size == 16

    def test_bracket_layer_names_do_not_collide_with_list_encoding(self):
        """A dict key shaped like '[0]' must round-trip as a DICT key,
        not be misparsed as a list element (keys escape '[')."""
        from deeplearning4j_tpu.utils.serializer import (
            _flatten_tree, _unflatten_tree)

        tree = {"[0]": {"W": np.ones((2, 2), np.float32)},
                "blocks": [{"W": np.zeros((1,), np.float32)},
                           {"W": np.ones((1,), np.float32)}]}
        back = _unflatten_tree(_flatten_tree(tree))
        assert isinstance(back, dict) and "[0]" in back
        assert isinstance(back["blocks"], list) and len(back["blocks"]) == 2
        np.testing.assert_array_equal(np.asarray(back["[0]"]["W"]),
                                      tree["[0]"]["W"])
        np.testing.assert_array_equal(np.asarray(back["blocks"][1]["W"]),
                                      tree["blocks"][1]["W"])


class TestTreeCodecFuzz:
    def test_randomized_tree_round_trip(self):
        """200 seeded random pytrees (nested dicts/lists, adversarial key
        names incl. '/', '%', '[i]' shapes) must round-trip through the
        flatten/npz/unflatten codec exactly."""
        import io
        import zipfile

        from deeplearning4j_tpu.utils.serializer import (
            _read_npz, _write_npz)

        keys = ["W", "b", "0_W", "a/b", "%2F", "[0]", "[x]", "blocks",
                "m", "layer.1", "%"]

        def rand_tree(rng, depth):
            kind = rng.integers(0, 3 if depth < 3 else 1)
            if kind == 0 or depth >= 3:
                shape = tuple(rng.integers(1, 4, rng.integers(0, 3)))
                return rng.normal(size=shape).astype(np.float32)
            if kind == 1:
                n = int(rng.integers(1, 4))
                picked = rng.choice(len(keys), size=n, replace=False)
                return {keys[i]: rand_tree(rng, depth + 1) for i in picked}
            return [rand_tree(rng, depth + 1)
                    for _ in range(int(rng.integers(1, 4)))]

        def assert_same(a, b, path=""):
            assert type(a) in (dict, list) and type(b) is type(a) \
                or not isinstance(a, (dict, list)), (path, type(a), type(b))
            if isinstance(a, dict):
                assert set(a) == set(b), (path, set(a), set(b))
                for k in a:
                    assert_same(a[k], b[k], f"{path}/{k}")
            elif isinstance(a, list):
                assert len(a) == len(b), path
                for i, (x, y) in enumerate(zip(a, b)):
                    assert_same(x, y, f"{path}[{i}]")
            else:
                np.testing.assert_array_equal(np.asarray(b),
                                              np.asarray(a), err_msg=path)

        rng = np.random.default_rng(42)
        for trial in range(200):
            # top level must be dict-or-list of entries (npz needs >= 1 key)
            tree = {"root": rand_tree(rng, 0)}
            buf = io.BytesIO()
            with zipfile.ZipFile(buf, "w") as zf:
                _write_npz(zf, "t.npz", tree)
            with zipfile.ZipFile(io.BytesIO(buf.getvalue())) as zf:
                back = _read_npz(zf, "t.npz")
            assert_same(tree, back, f"trial{trial}")
