"""SequenceVectors engine, Word2VecDataSetIterator, profiler listener
(reference: models/sequencevectors/SequenceVectors.java,
models/word2vec/iterator/Word2VecDataSetIterator.java)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import SequenceVectors, Word2VecDataSetIterator


def _walk_corpus():
    """Vertex-sequence corpus with two clusters: {a,b,c} and {x,y,z}."""
    rng = np.random.default_rng(0)
    groups = [["a", "b", "c"], ["x", "y", "z"]]
    seqs = []
    for _ in range(120):
        g = groups[rng.integers(0, 2)]
        seqs.append([g[i] for i in rng.integers(0, 3, 8)])
    return seqs


class TestSequenceVectors:
    def test_builder_and_cluster_structure(self):
        seqs = _walk_corpus()
        vec = (SequenceVectors.Builder()
               .iterate(seqs).layer_size(16).window_size(3)
               .negative_sample(4).epochs(8).seed(1)
               .min_element_frequency(1).build())
        vec.fit()
        assert vec.get_element_vector("a").shape == (16,)
        # co-occurring elements end up closer than cross-cluster ones
        for other in ("x", "y", "z"):
            assert vec.similarity("a", "b") > vec.similarity("a", other)
        assert vec.elements_nearest("a", top_n=1)[0] in {"b", "c"}

    def test_builder_requires_sequences(self):
        with pytest.raises(ValueError):
            SequenceVectors.Builder().build()

    def test_hs_mode(self):
        seqs = _walk_corpus()[:40]
        vec = (SequenceVectors.Builder().iterate(seqs).layer_size(8)
               .use_hierarchic_softmax(True).epochs(2).build())
        vec.fit()
        assert vec.get_element_vector("x") is not None

    def test_one_shot_generator_materialized(self):
        """A generator corpus must survive fit()'s two passes (vocab then
        pair emission)."""
        def gen():
            for _ in range(20):
                yield ["a", "b", "a", "b", "a"]

        vec = (SequenceVectors.Builder().iterate(gen()).layer_size(4)
               .epochs(1).build())
        vec.fit()
        # training actually ran: syn1neg moved off its zero init
        assert float(np.abs(np.asarray(vec.syn1neg)).sum()) > 0

    def test_non_string_elements_coerced(self):
        seqs = [[1, 2, 3, 1, 2], [2, 3, 1, 3, 2]] * 10
        vec = (SequenceVectors.Builder().iterate(seqs).layer_size(4)
               .epochs(1).build())
        vec.fit()
        assert vec.get_element_vector("1") is not None


class TestWord2VecDataSetIterator:
    def _vectors(self):
        seqs = _walk_corpus()
        return (SequenceVectors.Builder().iterate(seqs).layer_size(8)
                .window_size(3).epochs(1).build()).fit()

    def test_shapes_and_labels(self):
        vec = self._vectors()
        data = [(["a", "b", "c"], "pos"), (["x", "y"], "neg")]
        it = Word2VecDataSetIterator(vec, data, labels=["pos", "neg"],
                                     window_size=3, batch=4)
        assert it.total_examples() == 5  # 3 + 2 windows
        assert it.input_columns() == 3 * 8
        ds = it.next()
        assert ds.features.shape == (4, 24)
        assert ds.labels.shape == (4, 2)
        np.testing.assert_array_equal(ds.labels[0], [1, 0])
        # second batch is the remainder, then exhausted; reset restarts
        assert it.next().features.shape[0] == 1
        assert not it.has_next()
        it.reset()
        assert it.has_next()

    def test_padding_windows_are_zero(self):
        vec = self._vectors()
        it = Word2VecDataSetIterator(vec, [(["a"], "pos")], labels=["pos"],
                                     window_size=3)
        row = it.next(1).features[0].reshape(3, 8)
        assert np.all(row[0] == 0)  # <s> slot
        assert np.all(row[2] == 0)  # </s> slot
        assert not np.all(row[1] == 0)  # the word itself

    def test_unknown_label_rejected(self):
        vec = self._vectors()
        with pytest.raises(ValueError):
            Word2VecDataSetIterator(vec, [(["a"], "mystery")],
                                    labels=["pos"])

    def test_unfitted_vectors_rejected(self):
        from deeplearning4j_tpu.nlp import Word2Vec

        with pytest.raises(ValueError):
            Word2VecDataSetIterator(Word2Vec(), [], labels=["x"])

    def test_trains_downstream_classifier(self, rng):
        """End-to-end: embedding windows feed a dense classifier."""
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        vec = self._vectors()
        data = ([ (["a","b","c","a","b"], "pos") ] * 8
                + [ (["x","y","z","x","y"], "neg") ] * 8)
        it = Word2VecDataSetIterator(vec, data, labels=["pos", "neg"],
                                     window_size=3, batch=16)
        conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.05)
                .updater(Updater.ADAM).list()
                .layer(0, L.DenseLayer(n_in=it.input_columns(), n_out=16,
                                       activation="relu"))
                .layer(1, L.OutputLayer(n_in=16, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(it, num_epochs=20)
        it.reset()
        ev = net.evaluate(it)
        assert ev.accuracy() > 0.9, ev.accuracy()


class TestProfilerListener:
    def test_trace_written(self, tmp_path, rng):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf import layers as L
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.optimize.listeners import (
            ProfilerIterationListener)

        conf = (NeuralNetConfiguration.Builder().seed(0).learning_rate(0.1)
                .list()
                .layer(0, L.DenseLayer(n_in=4, n_out=8))
                .layer(1, L.OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        log_dir = str(tmp_path / "trace")
        lst = ProfilerIterationListener(log_dir, start_iteration=1,
                                        end_iteration=3)
        net.set_listeners(lst)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        for _ in range(5):
            net.fit(DataSet(x, y))
        assert not lst.active
        if not lst.failed:  # backend present: trace files must exist
            found = [f for _, _, fs in os.walk(log_dir) for f in fs]
            assert found, "no trace output written"

    def test_bad_window_rejected(self):
        from deeplearning4j_tpu.optimize.listeners import (
            ProfilerIterationListener)

        with pytest.raises(ValueError):
            ProfilerIterationListener("/tmp/x", 5, 5)
