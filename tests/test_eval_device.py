"""Device-resident evaluation + shape-bucketed inference path.

Covers the eval/inference acceptance criteria:
- host-side vectorized Evaluation.eval is byte-identical to the reference
  per-example loop (bincount vs dict-of-dicts)
- device-accumulated evaluate() == host-path evaluate() on every metric,
  with and without label masks, FF and RNN
- recompile guard: a ragged-tail batch stream compiles exactly one program
  per shape bucket for output/evaluate
- one-transfer-per-evaluate invariant (the [C, C] readback)
- device argmax predict(), ComputationGraph shared path, regression sums,
  BucketedDataSetIterator
"""

from collections import defaultdict

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    BucketedDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.eval.evaluation import (
    ConfusionMatrix,
    Evaluation,
    RegressionEvaluation,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.losses import LossFunction
from deeplearning4j_tpu.perf.bucketing import (
    bucket_size,
    pad_axis0,
    pad_dataset,
    padded_label_mask,
)


def mlp_net(d=8, classes=3, seed=7):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(seed).learning_rate(0.1).updater(Updater.SGD)
        .list()
        .layer(0, L.DenseLayer(n_in=d, n_out=16, activation="relu"))
        .layer(1, L.OutputLayer(n_in=16, n_out=classes,
                                loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def rnn_net(f=6, classes=4, seed=3):
    conf = (
        NeuralNetConfiguration.Builder()
        .seed(seed).learning_rate(0.1).updater(Updater.SGD)
        .list()
        .layer(0, L.GravesLSTM(n_in=f, n_out=12, activation="tanh"))
        .layer(1, L.RnnOutputLayer(n_in=12, n_out=classes,
                                   loss_function=LossFunction.MCXENT))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def classification_batches(rng, sizes, d=8, classes=3):
    out = []
    for n in sizes:
        x = rng.normal(size=(n, d)).astype(np.float32)
        y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
        out.append(DataSet(x, y))
    return out


def reference_loop_eval(labels, predictions, mask=None, num_classes=None):
    """The seed's per-example dict-of-dicts implementation, verbatim
    semantics — the byte-identity oracle for the vectorized host path."""
    labels = np.asarray(labels)
    predictions = np.asarray(predictions)
    if labels.ndim == 3:
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        predictions = predictions.reshape(b * t, c)
        if mask is not None:
            mask = np.asarray(mask).reshape(b * t)
    n = num_classes or labels.shape[-1]
    actual = np.argmax(labels, axis=-1)
    predicted = np.argmax(predictions, axis=-1)
    if mask is not None:
        keep = np.asarray(mask).astype(bool)
        actual, predicted = actual[keep], predicted[keep]
    matrix = defaultdict(lambda: defaultdict(int))
    for a, p in zip(actual, predicted):
        matrix[int(a)][int(p)] += 1
    out = np.zeros((n, n), np.int64)
    for a in range(n):
        for p in range(n):
            out[a, p] = matrix[a][p]
    return out


class TestVectorizedHostEval:
    def test_byte_identical_2d(self, rng):
        y = np.eye(5)[rng.integers(0, 5, 333)]
        p = rng.random((333, 5))
        ev = Evaluation()
        ev.eval(y, p)
        np.testing.assert_array_equal(ev.confusion.to_array(),
                                      reference_loop_eval(y, p))

    def test_byte_identical_3d_masked(self, rng):
        y = np.eye(4)[rng.integers(0, 4, (16, 9))]
        p = rng.random((16, 9, 4))
        mask = rng.integers(0, 2, (16, 9)).astype(np.float32)
        ev = Evaluation()
        ev.eval(y, p, mask=mask)
        np.testing.assert_array_equal(ev.confusion.to_array(),
                                      reference_loop_eval(y, p, mask=mask))

    def test_byte_identical_incremental(self, rng):
        """Multiple eval() calls accumulate identically to one loop pass."""
        ev = Evaluation()
        ref = np.zeros((3, 3), np.int64)
        for _ in range(4):
            y = np.eye(3)[rng.integers(0, 3, 50)]
            p = rng.random((50, 3))
            ev.eval(y, p)
            ref += reference_loop_eval(y, p)
        np.testing.assert_array_equal(ev.confusion.to_array(), ref)

    def test_empty_after_mask(self):
        ev = Evaluation()
        y = np.eye(3)[[0, 1]]
        p = np.eye(3)[[0, 1]]
        ev.eval(y, p, mask=np.zeros(2))
        assert ev.confusion.to_array().sum() == 0
        assert ev.accuracy() == 0.0

    def test_metrics_unchanged(self, rng):
        y = np.eye(4)[rng.integers(0, 4, 200)]
        p = rng.random((200, 4))
        ev = Evaluation()
        ev.eval(y, p)
        arr = reference_loop_eval(y, p)
        total, correct = arr.sum(), np.trace(arr)
        assert ev.accuracy() == pytest.approx(correct / total)
        for c in range(4):
            tp = arr[c, c]
            assert ev.true_positives(c) == tp
            assert ev.false_positives(c) == arr[:, c].sum() - tp
            assert ev.false_negatives(c) == arr[c].sum() - tp


class TestConfusionMatrix:
    def test_add_get_totals(self):
        cm = ConfusionMatrix([0, 1, 2])
        cm.add(0, 1)
        cm.add(0, 1)
        cm.add(2, 0, count=3)
        assert cm.get_count(0, 1) == 2
        assert cm.actual_total(0) == 2
        assert cm.predicted_total(0) == 3
        assert cm.predicted_total(1) == 2
        assert cm.get_count(1, 1) == 0

    def test_merge(self):
        a, b = ConfusionMatrix([0, 1]), ConfusionMatrix([0, 1])
        a.add(0, 0)
        b.add(0, 0)
        b.add(1, 0)
        a.merge(b)
        np.testing.assert_array_equal(a.to_array(), [[2, 0], [1, 0]])

    def test_out_of_range_grows(self):
        cm = ConfusionMatrix([0, 1])
        cm.add(4, 1)
        assert cm.get_count(4, 1) == 1
        assert cm.actual_total(4) == 1
        assert cm.to_array().shape == (5, 5)
        assert cm.get_count(9, 9) == 0  # read past the grid is 0, no grow


class TestBucketing:
    def test_ladder(self):
        assert bucket_size(1) == 1
        assert bucket_size(3) == 4
        assert bucket_size(64) == 64
        assert bucket_size(65) == 128
        assert bucket_size(5000) == 8192  # beyond ladder: multiple of top

    def test_disable_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_DISABLE_BUCKETING", "1")
        assert bucket_size(3) == 3

    def test_pad_axis0(self):
        a = np.arange(6, dtype=np.float32).reshape(3, 2)
        p = pad_axis0(a, 5)
        assert p.shape == (5, 2)
        np.testing.assert_array_equal(p[:3], a)
        np.testing.assert_array_equal(p[3:], 0)
        assert pad_axis0(a, 3) is a
        assert pad_axis0(None, 5) is None

    def test_padded_label_mask_created_and_extended(self):
        import jax.numpy as jnp

        y2 = jnp.ones((3, 4))
        m = padded_label_mask(y2, None, 8)
        assert m.shape == (8,)
        np.testing.assert_array_equal(np.asarray(m), [1] * 3 + [0] * 5)
        y3 = jnp.ones((2, 5, 4))
        m3 = padded_label_mask(y3, np.array([[1, 1, 0, 0, 0],
                                             [1, 1, 1, 1, 0]]), 4)
        assert m3.shape == (4, 5)
        assert np.asarray(m3)[2:].sum() == 0
        assert np.asarray(m3)[:2].sum() == 6

    def test_pad_dataset_always_has_labels_mask(self):
        ds = DataSet(np.ones((5, 3), np.float32), np.ones((5, 2), np.float32))
        p = pad_dataset(ds)
        assert p.features.shape == (8, 3)
        assert p.labels.shape == (8, 2)
        assert p.labels_mask is not None
        np.testing.assert_array_equal(np.asarray(p.labels_mask),
                                      [1] * 5 + [0] * 3)
        # exact-bucket batch STILL gets the mask (one jit signature/bucket)
        full = pad_dataset(DataSet(np.ones((8, 3), np.float32),
                                   np.ones((8, 2), np.float32)))
        assert full.labels_mask is not None
        assert np.asarray(full.labels_mask).sum() == 8


class TestDeviceEvalEquivalence:
    def test_mlp_device_matches_host(self, rng):
        net = mlp_net()
        batches = classification_batches(rng, [64, 64, 37])
        dev = net.evaluate(batches)
        host = net.evaluate(batches, device_accumulation=False)
        np.testing.assert_array_equal(dev.confusion.to_array(),
                                      host.confusion.to_array())
        for metric in ("accuracy", "precision", "recall", "f1"):
            assert getattr(dev, metric)() == pytest.approx(
                getattr(host, metric)()), metric

    def test_rnn_masked_device_matches_host(self, rng):
        net = rnn_net()
        batches = []
        for n in (16, 16, 9):
            x = rng.normal(size=(n, 7, 6)).astype(np.float32)
            y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (n, 7))]
            lengths = rng.integers(3, 8, n)
            lm = (np.arange(7)[None, :] < lengths[:, None]).astype(np.float32)
            batches.append(DataSet(x, y, labels_mask=lm))
        dev = net.evaluate(batches)
        host = net.evaluate(batches, device_accumulation=False)
        np.testing.assert_array_equal(dev.confusion.to_array(),
                                      host.confusion.to_array())
        assert dev.f1() == pytest.approx(host.f1())

    def test_rnn_unmasked_device_matches_host(self, rng):
        net = rnn_net()
        x = rng.normal(size=(11, 5, 6)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, (11, 5))]
        ds = DataSet(x, y)
        np.testing.assert_array_equal(
            net.evaluate(ds).confusion.to_array(),
            net.evaluate(ds, device_accumulation=False).confusion.to_array())

    def test_single_dataset_and_iterator_agree(self, rng):
        net = mlp_net()
        merged = DataSet.merge(classification_batches(rng, [100]))
        it = ListDataSetIterator(merged, batch_size=33)  # 33/33/33/1 tails
        np.testing.assert_array_equal(
            net.evaluate(it).confusion.to_array(),
            net.evaluate(merged).confusion.to_array())


class TestOneTransferInvariant:
    def test_one_device_to_host_conversion_measured(self, rng, monkeypatch):
        """Independent measurement, not the code's own counter: wrap
        numpy.asarray and count calls that receive a DEVICE array (each
        one is a device→host transfer). A whole multi-batch evaluate()
        must make exactly one — the [C, C] confusion readback."""
        import jax

        net = mlp_net()
        batches = classification_batches(rng, [32, 32, 32, 17])
        net.evaluate(batches)  # compile outside the measured window
        transfers = []
        real_asarray = np.asarray

        def counting_asarray(a, *args, **kwargs):
            if isinstance(a, jax.Array):
                transfers.append(a.shape)
            return real_asarray(a, *args, **kwargs)

        monkeypatch.setattr(np, "asarray", counting_asarray)
        try:
            ev = net.evaluate(batches)
        finally:
            monkeypatch.undo()
        assert transfers == [(3, 3)], transfers  # ONLY the [C, C] readback
        assert ev.confusion.to_array().sum() == 113

    def test_readback_counter_tracks_calls(self, rng):
        net = mlp_net()
        batches = classification_batches(rng, [32, 32, 32, 17])
        assert net._eval_readbacks == 0
        net.evaluate(batches)
        assert net._eval_readbacks == 1
        net.evaluate(batches)
        assert net._eval_readbacks == 2

    def test_empty_iterator_no_transfer(self):
        net = mlp_net()
        ev = net.evaluate([])
        assert net._eval_readbacks == 0
        assert ev.confusion is None


class TestRecompileGuard:
    """Count jit cache misses across ragged-tail batch streams: EXACTLY
    one compile per shape bucket for evaluate/output (acceptance
    criterion). Sizes 64/64/37/50 share buckets {64}, 100 adds {128}."""

    SIZES = [64, 64, 37, 50, 100]  # buckets: 64, 64, 64, 64, 128

    def test_evaluate_compiles_once_per_bucket(self, rng):
        net = mlp_net()
        batches = classification_batches(rng, self.SIZES)
        net.evaluate(batches)
        assert net._eval_step._cache_size() == 2
        # a second pass over the same stream: zero new compiles
        net.evaluate(batches)
        assert net._eval_step._cache_size() == 2

    def test_output_compiles_once_per_bucket(self, rng):
        net = mlp_net()
        for ds in classification_batches(rng, self.SIZES):
            net.output(ds.features)
        assert net._output_fn._cache_size() == 2

    def test_predict_compiles_once_per_bucket(self, rng):
        net = mlp_net()
        for ds in classification_batches(rng, self.SIZES):
            net.predict(ds.features)
        assert net._predict_fn._cache_size() == 2

    def test_score_compiles_once_per_bucket(self, rng):
        net = mlp_net()
        for ds in classification_batches(rng, self.SIZES):
            net.score(ds)
        assert net._score_fn._cache_size() == 2


class TestOutputAndPredict:
    def test_output_values_unchanged_by_padding(self, rng):
        """Pad rows must not leak into real rows: bucketed output ==
        exact-shape output (row-independent forward)."""
        net = mlp_net()
        x = rng.normal(size=(37, 8)).astype(np.float32)
        bucketed = np.asarray(net.output(x))
        import os

        os.environ["DL4J_DISABLE_BUCKETING"] = "1"
        try:
            exact = np.asarray(net.output(x))
        finally:
            del os.environ["DL4J_DISABLE_BUCKETING"]
        assert bucketed.shape == (37, 3)
        np.testing.assert_allclose(bucketed, exact, rtol=1e-6, atol=1e-7)

    def test_predict_matches_host_argmax(self, rng):
        net = mlp_net()
        x = rng.normal(size=(29, 8)).astype(np.float32)
        preds = net.predict(x)
        assert preds.shape == (29,)
        assert preds.dtype == np.int32
        np.testing.assert_array_equal(
            preds, np.argmax(np.asarray(net.output(x)), axis=-1))

    def test_score_value_unchanged_by_padding(self, rng):
        net = mlp_net()
        ds = classification_batches(rng, [37])[0]
        bucketed = net.score(ds)
        import os

        os.environ["DL4J_DISABLE_BUCKETING"] = "1"
        try:
            exact = net.score(ds)
        finally:
            del os.environ["DL4J_DISABLE_BUCKETING"]
        assert bucketed == pytest.approx(exact, rel=1e-5)


class TestGraphDeviceEval:
    @staticmethod
    def _toy_graph(seed=5):
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        g = (
            NeuralNetConfiguration.Builder()
            .seed(seed).learning_rate(0.05).updater(Updater.SGD)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", L.DenseLayer(n_in=6, n_out=8,
                                         activation="tanh"), "in")
            .add_layer("out", L.OutputLayer(
                n_in=8, n_out=3, loss_function=LossFunction.MCXENT), "d")
            .set_outputs("out")
        )
        return ComputationGraph(g.build()).init()

    def test_device_matches_host(self, rng):
        net = self._toy_graph()
        batches = []
        for n in (32, 32, 19):
            x = rng.normal(size=(n, 6)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
            batches.append(DataSet(x, y))
        dev = net.evaluate(batches)
        host = net.evaluate(batches, device_accumulation=False)
        np.testing.assert_array_equal(dev.confusion.to_array(),
                                      host.confusion.to_array())
        assert dev.accuracy() == pytest.approx(host.accuracy())
        assert net._eval_readbacks == 1

    def test_graph_compiles_once_per_bucket(self, rng):
        net = self._toy_graph()
        batches = [DataSet(rng.normal(size=(n, 6)).astype(np.float32),
                           np.eye(3, dtype=np.float32)[
                               rng.integers(0, 3, n)])
                   for n in (32, 32, 19, 25)]  # one bucket: 32
        net.evaluate(batches)
        assert net._eval_steps[0]._cache_size() == 1

    def test_graph_output_bucketed_values(self, rng):
        net = self._toy_graph()
        x = rng.normal(size=(19, 6)).astype(np.float32)
        out = net.output(x)[0]
        assert out.shape == (19, 3)
        np.testing.assert_allclose(np.asarray(out).sum(axis=1),
                                   np.ones(19), rtol=1e-5)


class TestRegressionDeviceEval:
    def _reg_net(self):
        conf = (
            NeuralNetConfiguration.Builder()
            .seed(11).learning_rate(0.05).updater(Updater.SGD)
            .list()
            .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, L.OutputLayer(n_in=8, n_out=2, activation="identity",
                                    loss_function=LossFunction.MSE))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    def test_sums_match_host_regression_eval(self, rng):
        net = self._reg_net()
        batches = [DataSet(rng.normal(size=(n, 4)).astype(np.float32),
                           rng.normal(size=(n, 2)).astype(np.float32))
                   for n in (32, 32, 21)]
        stats = net.evaluate_regression(batches)
        host = RegressionEvaluation()
        for ds in batches:
            host.eval(np.asarray(ds.labels), np.asarray(net.output(ds.features)))
        for c in range(2):
            assert stats.mean_squared_error(c) == pytest.approx(
                host.mean_squared_error(c), rel=1e-4)
            assert stats.mean_absolute_error(c) == pytest.approx(
                host.mean_absolute_error(c), rel=1e-4)
            assert stats.correlation_r2(c) == pytest.approx(
                host.correlation_r2(c), rel=1e-3, abs=1e-4)
            assert stats.pearson_correlation(c) == pytest.approx(
                host.pearson_correlation(c), rel=1e-3, abs=1e-4)
        assert stats.n == 85
        assert "MSE" in stats.stats()


class TestBucketedIterator:
    def test_pads_tail_and_masks(self, rng):
        ds = DataSet.merge(classification_batches(rng, [90]))
        it = BucketedDataSetIterator(ListDataSetIterator(ds, batch_size=64))
        batches = list(it)
        assert [b.features.shape[0] for b in batches] == [64, 32]
        tail = batches[1]
        np.testing.assert_array_equal(np.asarray(tail.labels_mask),
                                      [1] * 26 + [0] * 6)
        assert it.total_examples() == 90

    def test_training_and_eval_through_bucketed_iterator(self, rng):
        ds = DataSet.merge(classification_batches(rng, [90]))
        net = mlp_net()
        it = BucketedDataSetIterator(ListDataSetIterator(ds, batch_size=64))
        net.fit(it, num_epochs=2)
        assert net._train_step._cache_size() <= 2  # 64-bucket + 32-bucket
        ev = net.evaluate(it)
        host = net.evaluate(ds, device_accumulation=False)
        # pad rows are mask-inert: totals match the unpadded dataset
        assert ev.confusion.to_array().sum() == 90
        np.testing.assert_array_equal(ev.confusion.to_array(),
                                      host.confusion.to_array())
