"""NLP tests (Word2VecTests.java / GloveTest.java / ParagraphVectorsTest.java
analogues): vocab/Huffman invariants, embedding semantics on a synthetic
topic corpus, serializer round-trip, TF-IDF."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (
    BasicLineIterator,
    CollectionSentenceIterator,
    DefaultTokenizerFactory,
    Glove,
    ParagraphVectors,
    Word2Vec,
)
from deeplearning4j_tpu.nlp.bagofwords import BagOfWordsVectorizer, TfidfVectorizer
from deeplearning4j_tpu.nlp.sentence_iterator import LabelAwareSentenceIterator
from deeplearning4j_tpu.nlp.serializer import (
    load_binary,
    load_txt_vectors,
    load_word_vectors,
    write_binary,
    write_word_vectors,
)
from deeplearning4j_tpu.nlp.tokenization import (
    CommonPreprocessor,
    NGramTokenizerFactory,
)
from deeplearning4j_tpu.nlp.vocab import Huffman, build_vocab, unigram_table


def topic_corpus(n_sentences=400, seed=0):
    """Two disjoint topics; words within a topic co-occur."""
    rng = np.random.default_rng(seed)
    animals = ["cat", "dog", "horse", "cow", "sheep", "goat"]
    tech = ["cpu", "gpu", "ram", "disk", "cache", "bus"]
    sentences = []
    for _ in range(n_sentences):
        topic = animals if rng.random() < 0.5 else tech
        sentences.append(" ".join(rng.choice(topic, size=6)))
    return sentences


class TestTokenization:
    def test_default_tokenizer(self):
        tf = DefaultTokenizerFactory()
        assert tf.create("hello world foo").get_tokens() == ["hello", "world", "foo"]

    def test_preprocessor(self):
        tf = DefaultTokenizerFactory().set_token_pre_processor(CommonPreprocessor())
        assert tf.create("Hello, World!").get_tokens() == ["hello", "world"]

    def test_ngrams(self):
        tf = NGramTokenizerFactory(1, 2)
        toks = tf.create("a b c").get_tokens()
        assert toks == ["a", "b", "c", "a b", "b c"]


class TestVocab:
    def test_build_and_filter(self):
        vocab = build_vocab([["a", "a", "b"], ["a", "c"]], min_word_frequency=2)
        assert vocab.has_token("a") and not vocab.has_token("b")
        assert vocab.word_frequency("a") == 3
        # most frequent word gets index 0
        assert vocab.index_of("a") == 0

    def test_huffman_invariants(self):
        vocab = build_vocab([["a"] * 8 + ["b"] * 4 + ["c"] * 2 + ["d"]])
        Huffman(vocab).build()
        words = vocab.vocab_words()
        # frequent words get shorter codes
        assert len(words[0].codes) <= len(words[-1].codes)
        # points index inner nodes: < n-1
        for vw in words:
            assert (vw.points < vocab.num_words() - 1).all()
            assert set(np.unique(vw.codes)).issubset({0, 1})

    def test_unigram_table_distribution(self):
        vocab = build_vocab([["a"] * 100 + ["b"]])
        table = unigram_table(vocab, table_size=10000)
        # 'a' (index 0) should dominate
        assert (table == 0).mean() > 0.7


class TestWord2Vec:
    @pytest.mark.parametrize("mode", ["neg", "hs"])
    def test_topic_similarity(self, mode):
        vec = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(topic_corpus()))
               .min_word_frequency(1).layer_size(32).window_size(3)
               .negative_sample(0 if mode == "hs" else 5)
               .use_hierarchic_softmax(mode == "hs")
               .epochs(8).seed(1).learning_rate(0.05)
               .build())
        vec.fit()
        within = vec.similarity("cat", "dog")
        across = vec.similarity("cat", "gpu")
        assert within > across + 0.2, (mode, within, across)

    def test_words_nearest(self):
        vec = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(topic_corpus()))
               .min_word_frequency(1).layer_size(32).epochs(8).seed(1)
               .build())
        vec.fit()
        nearest = vec.words_nearest("cpu", top_n=3)
        tech = {"gpu", "ram", "disk", "cache", "bus"}
        assert len(tech.intersection(nearest)) >= 2, nearest

    def test_unknown_word(self):
        vec = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(["a b c d e f"] * 3))
               .min_word_frequency(1).layer_size(8).epochs(1).build())
        vec.fit()
        assert vec.get_word_vector("zzz") is None
        assert not vec.has_word("zzz")
        assert np.isnan(vec.similarity("a", "zzz"))

    def test_cbow_runs(self):
        vec = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(topic_corpus(100)))
               .elements_learning_algorithm("CBOW")
               .min_word_frequency(1).layer_size(16).epochs(2).build())
        vec.fit()
        assert vec.vocab_size() == 12


class TestSerializer:
    def _small_model(self):
        vec = (Word2Vec.Builder()
               .iterate(CollectionSentenceIterator(topic_corpus(50)))
               .min_word_frequency(1).layer_size(16).epochs(1).build())
        return vec.fit()

    def test_txt_roundtrip(self, tmp_path):
        model = self._small_model()
        path = str(tmp_path / "vecs.txt")
        write_word_vectors(model, path)
        vocab, syn0 = load_txt_vectors(path)
        assert vocab.num_words() == model.vocab_size()
        np.testing.assert_allclose(
            syn0[vocab.index_of("cat")],
            model.get_word_vector("cat"), atol=1e-5)

    def test_binary_roundtrip(self, tmp_path):
        model = self._small_model()
        path = str(tmp_path / "vecs.bin")
        write_binary(model, path)
        vocab, syn0 = load_binary(path)
        np.testing.assert_allclose(
            syn0[vocab.index_of("dog")],
            model.get_word_vector("dog"), atol=1e-6)

    def test_loaded_model_lookup_surface(self, tmp_path):
        model = self._small_model()
        path = str(tmp_path / "vecs.txt")
        write_word_vectors(model, path)
        loaded = load_word_vectors(path)
        assert loaded.similarity("cat", "cat") > 0.999
        assert loaded.words_nearest("cat", top_n=2)


class TestGlove:
    def test_topic_similarity(self):
        glove = (Glove.Builder()
                 .iterate(CollectionSentenceIterator(topic_corpus()))
                 .min_word_frequency(1).layer_size(16).window_size(3)
                 .epochs(25).seed(1)
                 .build())
        glove.fit()
        within = glove.similarity("cat", "dog")
        across = glove.similarity("cat", "gpu")
        assert within > across, (within, across)


class TestParagraphVectors:
    def test_label_vectors_cluster_by_topic(self):
        rng = np.random.default_rng(0)
        animals = ["cat dog horse cow", "dog sheep goat cat",
                   "horse cow cat dog"]
        tech = ["cpu gpu ram disk", "gpu cache bus cpu", "ram disk cpu gpu"]
        sentences = animals + tech
        labels = [f"A_{i}" for i in range(3)] + [f"T_{i}" for i in range(3)]
        pv = (ParagraphVectors.Builder()
              .iterate(LabelAwareSentenceIterator(sentences, labels))
              .min_word_frequency(1).layer_size(24).epochs(60)
              .learning_rate(0.05).seed(3)
              .build())
        pv.fit()
        va = [pv.get_label_vector(f"A_{i}") for i in range(3)]
        vt = [pv.get_label_vector(f"T_{i}") for i in range(3)]

        def cos(a, b):
            return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

        within = np.mean([cos(va[0], va[1]), cos(va[1], va[2]),
                          cos(vt[0], vt[1]), cos(vt[1], vt[2])])
        across = np.mean([cos(a, t) for a in va for t in vt])
        assert within > across, (within, across)

    def test_infer_and_predict(self):
        sentences = ["cat dog horse cow"] * 3 + ["cpu gpu ram disk"] * 3
        labels = [f"A_{i}" for i in range(3)] + [f"T_{i}" for i in range(3)]
        pv = (ParagraphVectors.Builder()
              .iterate(LabelAwareSentenceIterator(sentences, labels))
              .min_word_frequency(1).layer_size(16).epochs(200).learning_rate(0.1).seed(3)
              .build())
        pv.fit()
        assert pv.predict("cat dog cow").startswith("A_")
        assert pv.predict("gpu cpu disk").startswith("T_")


class TestVectorizers:
    DOCS = ["the cat sat", "the dog ran", "cat and dog"]

    def test_bow_counts(self):
        v = BagOfWordsVectorizer().fit(self.DOCS)
        x = v.transform("cat cat dog")
        assert x[v.vocab.index_of("cat")] == 2.0
        assert x[v.vocab.index_of("dog")] == 1.0

    def test_tfidf_downweights_common(self):
        v = TfidfVectorizer().fit(self.DOCS)
        x = v.transform("the cat")
        # 'the' appears in 2/3 docs, 'cat' in 2/3 — equal idf; use a rarer word
        x2 = v.transform("sat cat")
        assert x2[v.vocab.index_of("sat")] > x2[v.vocab.index_of("cat")]

    def test_vectorize_dataset(self):
        v = TfidfVectorizer().fit(self.DOCS)
        ds = v.vectorize(self.DOCS, labels=[0, 1, 0], num_classes=2)
        assert ds.features.shape == (3, v.vocab.num_words())
        np.testing.assert_array_equal(ds.labels.sum(axis=1), [1, 1, 1])


class TestGloveDiskSpill:
    def test_spill_matches_in_memory_counts(self, tmp_path):
        corpus = topic_corpus() * 6
        mem = (Glove.Builder()
               .iterate(CollectionSentenceIterator(corpus))
               .min_word_frequency(1).layer_size(8).window_size(3)
               .epochs(1).seed(1).build())
        mem.vocab = None
        from deeplearning4j_tpu.nlp.vocab import build_vocab
        mem.vocab = build_vocab(mem._sentences_tokens(), 1)
        r1, c1, x1 = mem.count_cooccurrences()
        assert mem.spill_count == 0

        spill = Glove(CollectionSentenceIterator(corpus),
                      min_word_frequency=1, layer_size=8, window_size=3,
                      epochs=1, seed=1, max_memory_pairs=7,
                      spill_dir=str(tmp_path / "cooc"))
        spill.vocab = build_vocab(spill._sentences_tokens(), 1)
        r2, c2, x2 = spill.count_cooccurrences()
        assert spill.spill_count > 1  # multiple shards actually written

        def as_map(r, c, x):
            return {(int(a), int(b)): float(v) for a, b, v in zip(r, c, x)}

        m1, m2 = as_map(r1, c1, x1), as_map(r2, c2, x2)
        assert set(m1) == set(m2)
        for k in m1:
            assert abs(m1[k] - m2[k]) < 1e-4, k

    def test_spilled_glove_still_learns(self, tmp_path):
        glove = Glove(CollectionSentenceIterator(topic_corpus()),
                      min_word_frequency=1, layer_size=16, window_size=3,
                      epochs=25, seed=1, max_memory_pairs=5,
                      spill_dir=str(tmp_path / "cooc"))
        glove.fit()
        assert glove.spill_count > 0
        assert glove.similarity("cat", "dog") > glove.similarity("cat", "gpu")
