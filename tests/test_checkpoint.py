"""Orbax checkpoint round-trips (utils/checkpoint.py).

Covers the restore path with non-array leaves (python ints) that the
abstract-target builder must coerce — a save/restore cycle on a trained
network including updater state and the scalar iteration counter.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils.checkpoint import (
    latest_step,
    restore_checkpoint,
    restore_network,
    save_checkpoint,
    save_network,
)


def _trained_net(seed=0, steps=3):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).list()
        .layer(0, L.DenseLayer(n_in=4, n_out=8, activation="relu"))
        .layer(1, L.OutputLayer(n_in=8, n_out=3))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3)[rng.integers(0, 3, 16)].astype(np.float32)
        net.fit(DataSet(x, y))
    return net


class TestTransformerShardedCheckpoint:
    """TransformerLM through save_network/restore_network, including a
    TP-SHARDED state: Orbax writes each shard from where it lives and
    restores onto the target's shardings — the multi-host path the zip
    serializer's fully-addressable guard points at."""

    def _lm(self, seed=0):
        from deeplearning4j_tpu.models.transformer import TransformerLM

        return TransformerLM(vocab_size=32, d_model=32, num_heads=4,
                             num_layers=1, max_len=16, lr=5e-3,
                             seed=seed).init()

    def test_transformer_round_trip(self, tmp_path):
        import jax.numpy as jnp

        lm = self._lm()
        tok = jnp.asarray(np.tile(np.arange(8), (4, 2)), jnp.int32)
        step = lm.make_train_step(donate=False)
        for _ in range(3):
            lm.fit_batch(tok, train_step=step)
        save_network(str(tmp_path), lm, step=3)
        other = self._lm(seed=1)
        restore_network(str(tmp_path), other)
        np.testing.assert_array_equal(
            np.asarray(other.params["embed"]),
            np.asarray(lm.params["embed"]))
        assert other.step_count == lm.step_count
        # optimizer moments restored: next identical step stays in sync
        s2 = other.make_train_step(donate=False)
        l1 = lm.fit_batch(tok, train_step=step)
        l2 = other.fit_batch(tok, train_step=s2)
        assert l1 == pytest.approx(l2, rel=1e-5)

    def test_tp_sharded_round_trip(self, tmp_path):
        from deeplearning4j_tpu.parallel import MeshSpec, build_mesh

        lm = self._lm()
        mesh = build_mesh(MeshSpec(data=4, model=2))
        lm.shard_params(mesh)
        save_network(str(tmp_path), lm, step=1)
        other = self._lm(seed=2)
        other.shard_params(mesh)
        restore_network(str(tmp_path), other)
        wq = other.params["blocks"][0]["attn"]["wq"]
        # restored ONTO the target's TP sharding, not gathered/replicated
        assert "model" in (wq.sharding.spec or ())
        np.testing.assert_array_equal(
            np.asarray(wq), np.asarray(lm.params["blocks"][0]["attn"]["wq"]))


class TestCheckpointRoundTrip:
    def test_pytree_with_scalar_leaves(self, tmp_path):
        state = {
            "params": {"W": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
            "iteration": 7,
            "lr": 0.125,
        }
        save_checkpoint(str(tmp_path), state, step=7)
        assert latest_step(str(tmp_path)) == 7
        # target=None path
        plain = restore_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(plain["params"]["W"],
                                      np.asarray(state["params"]["W"]))
        # target path with python int/float leaves (the round-1 crash)
        out = restore_checkpoint(str(tmp_path), target=state)
        assert int(out["iteration"]) == 7
        assert float(out["lr"]) == pytest.approx(0.125)
        np.testing.assert_array_equal(np.asarray(out["params"]["W"]),
                                      np.asarray(state["params"]["W"]))

    def test_checkpoint_iteration_listener(self, tmp_path):
        """CheckpointIterationListener writes iteration-keyed Orbax
        checkpoints mid-training that restore_network resumes from."""
        from deeplearning4j_tpu.optimize import CheckpointIterationListener

        net = _trained_net(steps=0)
        net.set_listeners(CheckpointIterationListener(
            str(tmp_path), frequency=2, keep=2))
        x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[
            np.random.default_rng(1).integers(0, 3, 8)]
        from deeplearning4j_tpu.datasets.dataset import DataSet

        for _ in range(4):
            net.fit(DataSet(x, y))
        net.listeners[0].close()  # drain async saves
        assert latest_step(str(tmp_path)) == 4
        other = _trained_net(seed=5, steps=0)
        restore_network(str(tmp_path), other)
        np.testing.assert_allclose(other.get_flat_params(),
                                   net.get_flat_params(), rtol=0, atol=0)
        assert other.iteration_count == 4

    def test_listener_stride_survives_fused_iteration_jumps(self,
                                                            tmp_path):
        """Fused drivers (fit_steps) jump iteration_count by K per
        listener firing; the save stride is >= based, not exact-modulo,
        so checkpoints never become K-times rarer than configured."""
        from deeplearning4j_tpu.optimize import CheckpointIterationListener

        net = _trained_net(steps=0)
        lst = CheckpointIterationListener(str(tmp_path), frequency=10)
        # iteration jumps of 7: exact-modulo would first fire at 70
        for it in (7, 14, 21, 28):
            lst.iteration_done(net, it)
        lst.close()
        # >= stride saves at 14 (Δ14) and 28 (Δ14), never waits for 70
        assert latest_step(str(tmp_path)) == 28

    def test_listener_stride_survives_fit_epochs_jumps(self, tmp_path):
        """fit_epochs jumps iteration_count by chunk_epochs*N per listener
        firing (E*N for a fully-fused chunk) — larger jumps than fit_steps'
        K. The >= stride must keep firing at every multiple-crossing and
        the saved step must be the jumped count, resumable as usual."""
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.optimize import CheckpointIterationListener

        net = _trained_net(steps=0)
        lst = CheckpointIterationListener(str(tmp_path), frequency=6)
        net.set_listeners(lst)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = np.eye(3)[rng.integers(0, 3, 64)].astype(np.float32)
        # listeners attached → chunk of 1 epoch → jumps of N=4: fires at
        # 8 (Δ8 ≥ 6), not 12 (Δ4), 16 (Δ8), not 20, 24 — never modulo-6
        hist = net.fit_epochs(ListDataSetIterator(DataSet(x, y), 16), 6)
        assert hist is not None and net.iteration_count == 24
        lst.close()
        assert latest_step(str(tmp_path)) == 24
        other = _trained_net(seed=5, steps=0)
        restore_network(str(tmp_path), other)
        assert other.iteration_count == 24
        np.testing.assert_array_equal(other.get_flat_params(),
                                      net.get_flat_params())

    def test_zero_size_leaves_round_trip(self, tmp_path):
        """SGD/NONE updater state holds zeros((0,)) placeholders, which
        Orbax refuses to serialize — they are stripped at save and
        reinstated from the target at restore."""
        state = {
            "params": {"W": jnp.ones((2, 2))},
            "updater_state": {"W": jnp.zeros((0,), jnp.float32)},
            "iteration": 3,
        }
        save_checkpoint(str(tmp_path), state, step=3)
        out = restore_checkpoint(str(tmp_path), target=state)
        np.testing.assert_array_equal(np.asarray(out["params"]["W"]),
                                      np.ones((2, 2)))
        assert out["updater_state"]["W"].shape == (0,)
        assert int(out["iteration"]) == 3

    def test_network_save_restore(self, tmp_path):
        net = _trained_net()
        save_network(str(tmp_path), net)
        ref_params = net.get_flat_params()
        ref_iter = net.iteration_count

        other = _trained_net(seed=1, steps=1)
        restore_network(str(tmp_path), other)
        np.testing.assert_allclose(other.get_flat_params(), ref_params,
                                   rtol=0, atol=0)
        assert other.iteration_count == ref_iter
        # updater state restored: one more identical fit step stays in sync
        x = np.zeros((4, 4), np.float32)
        y = np.eye(3)[[0, 1, 2, 0]].astype(np.float32)
        net.fit(DataSet(x, y))
        other.fit(DataSet(x, y))
        np.testing.assert_allclose(other.get_flat_params(),
                                   net.get_flat_params(), rtol=1e-6,
                                   atol=1e-7)

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(str(tmp_path / "empty"))
