"""Fused whole-epoch skip-gram + GloVe (nlp/epoch_kernels, ISSUE 18).

The equivalence contract under test: the in-program pair generator is a
pure function of per-epoch ``jax.random`` keys, so the SAME derivation
run eagerly (host reference) and traced (fused chunk program) consumes
identical RNG streams — the fused path is tested against an eager
replay of itself plus, at window=1 (where the reduced window is
deterministic), against the legacy host emitter's exact pair multiset.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp import Word2Vec
from deeplearning4j_tpu.nlp.epoch_kernels import (
    SkipGramCorpusCache,
    _neg_epoch_impl,
    epoch_keys_for,
    skipgram_epoch_plan,
    skipgram_pair_plan,
)
from deeplearning4j_tpu.nlp.sentence_iterator import (
    CollectionSentenceIterator,
)


def _sentences(rng, n_words=40, n_sent=70, lo=3, hi=12):
    words = [f"w{i}" for i in range(n_words)]
    return [" ".join(rng.choice(words, size=rng.integers(lo, hi)))
            for _ in range(n_sent)]


def _w2v(sents, **kw):
    kw.setdefault("min_word_frequency", 1)
    kw.setdefault("layer_size", 16)
    kw.setdefault("window_size", 3)
    kw.setdefault("negative", 5)
    kw.setdefault("seed", 0)
    kw.setdefault("epochs", 2)
    w = Word2Vec(sentence_iterator=CollectionSentenceIterator(sents), **kw)
    w.build_vocab()
    w.reset_weights()
    return w


class TestPairPlanEquivalence:
    def test_window1_matches_host_emitter_multiset(self, rng):
        """At window=1 the reduced window b ~ U{1..1} is deterministic, so
        the fused plan's valid pairs must be EXACTLY the host emitter's
        multiset (sampling off ⇒ no RNG in either path's selection)."""
        w2v = _w2v(_sentences(rng), window_size=1, sampling=0.0)
        sentences = w2v._corpus_indices(subsample=False)
        host_c, host_x = w2v._emit_pairs(sentences)

        cache = SkipGramCorpusCache.build(w2v)
        cen, ctx, val = skipgram_pair_plan(
            jax.random.PRNGKey(7), cache.tokens, cache.mask,
            cache.keep_prob, cache.window)
        m = np.asarray(val) > 0
        fused = sorted(zip(np.asarray(cen)[m].tolist(),
                           np.asarray(ctx)[m].tolist()))
        host = sorted(zip(host_c.tolist(), host_x.tolist()))
        assert fused == host

    def test_plan_is_key_deterministic(self, rng):
        w2v = _w2v(_sentences(rng))
        cache = SkipGramCorpusCache.build(w2v)
        k = jax.random.PRNGKey(3)
        a = skipgram_epoch_plan(k, cache.tokens, cache.mask,
                                cache.keep_prob, cache.table, cache.window,
                                cache.negative, cache.n_batches, cache.batch)
        b = skipgram_epoch_plan(k, cache.tokens, cache.mask,
                                cache.keep_prob, cache.table, cache.window,
                                cache.negative, cache.n_batches, cache.batch)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_negative_draw_distribution_follows_table(self, rng):
        """The in-program unigram draws must replay the host emitter's
        DISTRIBUTION: empirical negative frequencies track the table's
        composition (frequent rows drawn proportionally more)."""
        w2v = _w2v(_sentences(rng), negative=5)
        cache = SkipGramCorpusCache.build(w2v)
        _, _, _, negs = skipgram_epoch_plan(
            jax.random.PRNGKey(11), cache.tokens, cache.mask,
            cache.keep_prob, cache.table, cache.window, cache.negative,
            cache.n_batches, cache.batch)
        draws = np.asarray(negs).ravel()
        table = np.asarray(cache.table)
        v = w2v.vocab.num_words()
        emp = np.bincount(draws, minlength=v) / len(draws)
        ref = np.bincount(table, minlength=v) / len(table)
        # collision redraws perturb the marginal slightly; 3x total
        # variation headroom still separates it cleanly from uniform
        assert np.abs(emp - ref).sum() < 3 * np.abs(
            ref - 1.0 / v).sum() + 0.05


class TestFusedEquivalence:
    def test_fused_matches_eager_replay(self, rng):
        """E fused epochs == the same plan applied per batch eagerly
        (same keys, same LR schedule) — tracing must not change math."""
        sents = _sentences(rng)
        fused = _w2v(sents, epochs=2)
        cache = fused.build_corpus_cache()
        hist = fused.fit_epochs(2)
        assert hist.shape == (2, cache.n_batches)

        ref = _w2v(sents, epochs=2)
        s0, s1 = ref.syn0, ref.syn1neg
        keys = epoch_keys_for(ref.seed, 0, 2)
        planned = 2 * cache.n_batches
        it = 0
        ref_hist = np.zeros((2, cache.n_batches), np.float32)
        for e in range(2):
            cen, ctx, val, neg = skipgram_epoch_plan(
                keys[e], cache.tokens, cache.mask, cache.keep_prob,
                cache.table, cache.window, cache.negative,
                cache.n_batches, cache.batch)
            for n in range(cache.n_batches):
                lr = max(ref.min_learning_rate,
                         ref.learning_rate * (1.0 - it / planned))
                s0, s1, loss = _neg_epoch_impl(
                    s0, s1, cen[n], ctx[n], val[n], neg[n],
                    jnp.asarray(lr, jnp.float32))
                ref_hist[e, n] = float(loss)
                it += 1
        np.testing.assert_allclose(np.asarray(hist), ref_hist, atol=1e-5)
        np.testing.assert_allclose(np.asarray(fused.syn0), np.asarray(s0),
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(fused.syn1neg),
                                   np.asarray(s1), atol=1e-5)

    def test_one_dispatch_per_chunk(self, rng):
        w2v = _w2v(_sentences(rng), epochs=4)
        w2v.fit_epochs(4)
        assert w2v._train_dispatches == 1
        chunked = _w2v(_sentences(rng), epochs=4)
        chunked.fit_epochs(4, chunk_epochs=1)
        assert chunked._train_dispatches == 4

    def test_listeners_fire_per_chunk(self, rng):
        calls = []

        class Listener:
            def chunk_done(self, model, it0, hist, metrics=None):
                calls.append((it0, tuple(hist.shape)))

        w2v = _w2v(_sentences(rng), epochs=3)
        w2v.listeners.append(Listener())
        w2v.fit_epochs(3)  # listeners present → chunk_epochs defaults to 1
        assert w2v._train_dispatches == 3
        assert len(calls) == 3

    def test_resume_mid_run_determinism(self, rng):
        """fit_epochs(2) twice must equal fit_epochs(4) one-shot: epoch
        keys fold in the ABSOLUTE epoch index and the LR schedule decays
        over the configured horizon, so chunk boundaries are invisible."""
        sents = _sentences(rng)
        split = _w2v(sents, epochs=4)
        cache_s = split.build_corpus_cache()
        h1 = split.fit_epochs(2)
        h2 = split.fit_epochs(2)
        oneshot = _w2v(sents, epochs=4)
        cache_o = SkipGramCorpusCache.build(oneshot, batch=cache_s.batch)
        h = oneshot.fit_epochs(4, cache=cache_o)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(h1), np.asarray(h2)]),
            np.asarray(h))
        np.testing.assert_allclose(np.asarray(split.syn0),
                                   np.asarray(oneshot.syn0), atol=1e-6)

    def test_preemption_hook_stops_between_chunks(self, rng):
        w2v = _w2v(_sentences(rng), epochs=4)
        hist = w2v.fit_epochs(4, chunk_epochs=1,
                              on_chunk=lambda done: done >= 2)
        assert hist.shape[0] == 2
        assert w2v._epochs_done == 2


class TestCorpusCacheEdgeCases:
    def test_ragged_last_bucket_and_length_one_sentences(self, rng):
        """Ragged sentence lengths (incl. a length-1 sentence the index
        pass drops) bucket-pad instead of crashing; pads emit no pairs."""
        sents = ["w0 w1 w2 w3 w4 w5 w6", "w0 w1", "w2", "w3 w4 w5"]
        w2v = _w2v(sents, window_size=2, negative=3)
        cache = w2v.build_corpus_cache()
        assert cache is not None
        assert cache.tokens.shape[0] == 3  # the length-1 sentence dropped
        hist = w2v.fit_epochs(2)
        assert hist is not None
        assert np.isfinite(np.asarray(w2v.syn0)).all()

    def test_vocab_smaller_than_negative_count(self, rng):
        """3-word vocab, 10 negatives per pair: draws repeat, training
        stays finite (the reference's redraw loop tolerates this too)."""
        sents = ["a b c a b c a", "b c a b", "c a b c a b"]
        w2v = _w2v(sents, window_size=2, negative=10, layer_size=8)
        assert w2v.vocab.num_words() == 3
        hist = w2v.fit_epochs(2)
        assert hist is not None
        assert np.isfinite(np.asarray(hist)).all()
        assert np.isfinite(np.asarray(w2v.syn0)).all()

    def test_subsample_everything_corpus(self, rng):
        """A sampling threshold so aggressive every token is dropped:
        zero valid pairs, zero loss, tables untouched (masked updater)."""
        # default corpus geometry on purpose: shares the memoized fused
        # program with the equivalence tests (sampling only changes the
        # keep_prob VALUES, not the compiled program)
        w2v = _w2v(_sentences(rng), sampling=1e-12, epochs=2)
        before0 = np.asarray(w2v.syn0).copy()
        before1 = np.asarray(w2v.syn1neg).copy()
        hist = w2v.fit_epochs(2)
        assert hist is not None
        np.testing.assert_array_equal(np.asarray(hist),
                                      np.zeros_like(np.asarray(hist)))
        np.testing.assert_array_equal(np.asarray(w2v.syn0), before0)
        np.testing.assert_array_equal(np.asarray(w2v.syn1neg), before1)

    def test_over_budget_falls_back_to_host(self, rng):
        w2v = _w2v(_sentences(rng), epochs=1)
        assert w2v.build_corpus_cache(budget_mb=0) is None
        hist = w2v.fit_epochs(1, budget_mb=0)
        assert hist is None  # host loop ran instead
        assert w2v._train_dispatches == 0
        assert np.isfinite(np.asarray(w2v.syn0)).all()

    def test_fused_disabled_env_falls_back(self, rng, monkeypatch):
        monkeypatch.setenv("DL4J_W2V_FUSED", "0")
        w2v = _w2v(_sentences(rng), epochs=1)
        assert w2v.fit_epochs(1) is None
        assert w2v._train_dispatches == 0

    def test_hs_and_cbow_fall_back(self, rng):
        hs = _w2v(_sentences(rng), hierarchic_softmax=True, epochs=1)
        assert hs.fit_epochs(1) is None
        cbow = _w2v(_sentences(rng), algorithm="cbow", epochs=1)
        assert cbow.fit_epochs(1) is None


class TestEmbeddingContracts:
    def test_single_device_program_contracts(self, rng):
        """PR-7 checks over the cached fused program: no callbacks, NO
        collectives at all single-device, both tables donated, outputs
        (syn0, syn1neg, hist[E, N])."""
        from deeplearning4j_tpu.analysis.contracts import (
            check_embedding_contracts,
        )

        w2v = _w2v(_sentences(rng), epochs=2)
        w2v.fit_epochs(2)
        results = check_embedding_contracts(w2v, w2v._corpus_cache,
                                            epochs=2)
        assert all(not v for v in results.values())

    def test_empty_program_cache_raises(self, rng):
        from deeplearning4j_tpu.analysis.contracts import (
            check_embedding_contracts,
        )

        w2v = _w2v(_sentences(rng))
        cache = w2v.build_corpus_cache()
        with pytest.raises(ValueError, match="no cached fused"):
            check_embedding_contracts(w2v, cache)


class TestGloveFused:
    def test_fused_matches_host_reference(self, rng):
        """One fused GloVe run == per-batch eager application of the
        same masked AdaGrad step with the same in-program shuffle keys
        (duplicate rows in a batch exercise _row_scale's joint count)."""
        from deeplearning4j_tpu.nlp import Glove
        from deeplearning4j_tpu.nlp.glove import _glove_step_math
        from deeplearning4j_tpu.nlp.vocab import build_vocab

        sents = _sentences(rng, n_words=25, n_sent=80)
        g = Glove(sentence_iterator=CollectionSentenceIterator(sents),
                  min_word_frequency=1, layer_size=8, window_size=3,
                  epochs=3, seed=0)
        g.fit()
        assert g._train_dispatches == 1

        ref = Glove(sentence_iterator=CollectionSentenceIterator(sents),
                    min_word_frequency=1, layer_size=8, window_size=3,
                    epochs=3, seed=0)
        ref.vocab = build_vocab(ref._sentences_tokens(), 1)
        rows, cols, x = ref.count_cooccurrences()
        n, d = ref.vocab.num_words(), ref.layer_size
        k1, k2 = jax.random.split(jax.random.PRNGKey(ref.seed))
        scale = 0.5 / d
        tbl = (jax.random.uniform(k1, (n, d), jnp.float32, -scale, scale),
               jax.random.uniform(k2, (n, d), jnp.float32, -scale, scale),
               jnp.zeros((n,)), jnp.zeros((n,)),
               jnp.full((n, d), 1e-8), jnp.full((n, d), 1e-8),
               jnp.full((n,), 1e-8), jnp.full((n,), 1e-8))
        logx = np.log(np.maximum(x, 1e-12)).astype(np.float32)
        fx = np.minimum(1.0, (x / ref.x_max) ** ref.alpha).astype(
            np.float32)
        batch = min(ref.batch_size, max(32, len(rows) // 8))
        total = -(-len(rows) // batch) * batch
        pad = total - len(rows)
        rows = np.pad(rows.astype(np.int32), (0, pad))
        cols = np.pad(cols.astype(np.int32), (0, pad))
        logx, fx = np.pad(logx, (0, pad)), np.pad(fx, (0, pad))
        base = jax.random.PRNGKey(ref.seed)
        epoch_keys = jax.vmap(lambda e: jax.random.fold_in(base, e))(
            jnp.arange(ref.epochs))
        for e in range(ref.epochs):
            order = np.asarray(jax.random.permutation(epoch_keys[e],
                                                      total))
            for s in range(0, total, batch):
                sel = order[s:s + batch]
                *tbl, _ = _glove_step_math(
                    *tbl, jnp.asarray(rows[sel]), jnp.asarray(cols[sel]),
                    jnp.asarray(logx[sel]), jnp.asarray(fx[sel]),
                    jnp.asarray(ref.learning_rate, jnp.float32))
                tbl = tuple(tbl)
        host_syn0 = np.asarray(tbl[0]) + np.asarray(tbl[1])
        np.testing.assert_allclose(g.syn0, host_syn0, atol=1e-5)

    def test_padded_triples_are_inert(self, rng):
        """fx=0 pad triples: zero gradient, zero accumulator growth, and
        excluded from the loss mean."""
        from deeplearning4j_tpu.nlp.glove import _glove_step_math

        n, d, b = 6, 4, 8
        key = jax.random.PRNGKey(0)
        w = jax.random.normal(key, (n, d)) * 0.1
        tbl = (w, w + 0.01, jnp.zeros((n,)), jnp.zeros((n,)),
               jnp.full((n, d), 1e-8), jnp.full((n, d), 1e-8),
               jnp.full((n,), 1e-8), jnp.full((n,), 1e-8))
        rows = jnp.asarray([0, 1, 2, 0, 0, 0, 0, 0], jnp.int32)
        cols = jnp.asarray([1, 2, 3, 0, 0, 0, 0, 0], jnp.int32)
        logx = jnp.asarray([0.5, 0.2, 0.1, 0, 0, 0, 0, 0], jnp.float32)
        fx = jnp.asarray([1, 1, 1, 0, 0, 0, 0, 0], jnp.float32)
        lr = jnp.asarray(0.05, jnp.float32)
        *out_pad, loss_pad = _glove_step_math(*tbl, rows, cols, logx, fx,
                                              lr)
        *out_ref, loss_ref = _glove_step_math(
            *tbl, rows[:3], cols[:3], logx[:3], fx[:3], lr)
        np.testing.assert_allclose(float(loss_pad), float(loss_ref),
                                   atol=1e-6)
        for a, b_ in zip(out_pad, out_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=1e-6)
