"""Serve-side overload control: deadlines, criticality shedding, retry
budgets, hedging, and graceful drain.

The load-bearing claims, each asserted mechanically here:

1. **Deadlines shed at the earliest point.** An expired request is
   refused at admission, swept from the queue, or retired mid-flight —
   whichever comes first — and every shed decision leaves evidence
   (``shed_log`` + ``serve.shed`` tracer event + counters), split
   ``expired_in_queue`` vs ``expired_in_flight``.
2. **Criticality displacement never eats its own class.** At the queue
   bound an arrival may shed the costliest queued request of a STRICTLY
   lower class; an all-interactive overload sheds the newcomer, never a
   peer.
3. **Retries are budgeted.** Failover re-dispatch and hedges draw from
   per-class token buckets (``submitted * (1+ratio) + burst`` cap);
   a dry bucket parks the retry instead of amplifying the storm.
4. **Hedges are safe bets.** A tail-stuck interactive request races a
   second greedy copy; first winner cancels the loser, token-identical
   either way.
5. **Drain loses nothing.** ``FleetController.drain`` quiesces, stops
   the loop, migrates queued work and live KV slabs to survivors —
   zero recompute, zero lost tokens, no failover counter movement.
6. **The storm soak.** 3x-capacity Poisson load with a criticality mix
   and a mid-storm drain: interactive p50 TTFT holds within 2x the
   uncontended baseline, only batch/best-effort or past-deadline
   requests are shed, retry amplification stays under 1.2x, and the
   drained replica retires with zero lost tokens. This is the
   ``scripts/verify.sh --serve-slo`` gate.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.monitor import metrics, tracer
from deeplearning4j_tpu.monitor.trace import SpanTracer, set_tracer
from deeplearning4j_tpu.serving import (
    RetryBudget, DecodeServer, poisson_schedule)
from deeplearning4j_tpu.serving.scheduler import RequestQueue, ServeRequest
from deeplearning4j_tpu.serving.fleet import (
    FleetController, FleetLoadDriver, FleetRouter, ServeReplica)

_LM_CACHE = {}


def _lm(key="greedy", **kw):
    """One tiny model per config, cached for the module (same idiom as
    test_serving_fleet: many servers, one compile)."""
    if key not in _LM_CACHE:
        cfg = dict(vocab_size=61, d_model=32, num_heads=4,
                   num_kv_heads=2, num_layers=2, max_len=96, seed=3,
                   pos_encoding="rope")
        cfg.update(kw)
        _LM_CACHE[key] = TransformerLM(**cfg).init()
    return _LM_CACHE[key]


def _replica(rid, lm=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    return ServeReplica(rid, lm if lm is not None else _lm(), **kw)


def _ref(lm, prompt, n, **kw):
    return np.asarray(lm.generate(np.asarray(prompt)[None], n, **kw))[0]


def _server(**kw):
    kw.setdefault("slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("max_queue", 4)
    return DecodeServer(_lm(), **kw)


def _prompt(n=4):
    return np.arange(1, n + 1, dtype=np.int32)


# ---------------------------------------------------------------------------
# deadlines: shed at the earliest point, with evidence
# ---------------------------------------------------------------------------
class TestDeadlineSheds:
    def test_expired_at_admission(self):
        server = _server()
        t = {"now": 10.0}
        server.clock = lambda: t["now"]
        v = server.try_submit(_prompt(), 4, deadline_s=9.0)
        assert not v.admitted and v.reason == "expired"
        assert server.shed_log[-1]["where"] == "admission"
        assert server.shed_log[-1]["reason"] == "deadline"
        assert server.shed[-1].state == "shed"
        # never cost a queue entry
        assert len(server.queue) == 0

    def test_expired_in_queue_swept_at_admit(self):
        server = _server(slots=1)
        t = {"now": 0.0}
        server.clock = lambda: t["now"]
        # fills the single slot
        v1 = server.try_submit(_prompt(), 2, deadline_s=100.0)
        server.step()
        # queued behind it with a tight deadline
        v2 = server.try_submit(_prompt(5), 4, deadline_s=0.5)
        assert v1.admitted and v2.admitted
        # expiry is observed at the pop — run the slot dry so admission
        # reaches the corpse rather than burning a prefill on it
        while v1.request.state != "finished":
            server.step()
        t["now"] = 1.0
        server.step()
        assert v2.request.state == "shed"
        assert v2.request.shed_reason == "deadline"
        assert server.stats()["expired_in_queue"] == 1
        assert server.stats()["expired_in_flight"] == 0

    def test_expired_in_flight_frees_slot(self):
        server = _server(slots=1)
        t = {"now": 0.0}
        server.clock = lambda: t["now"]
        v = server.try_submit(_prompt(), 8, deadline_s=0.5)
        server.step()                       # admitted + decoding
        assert v.request.state == "running"
        t["now"] = 1.0
        server.step()                       # sweep retires it
        assert v.request.state == "shed"
        assert server.stats()["expired_in_flight"] == 1
        # the freed slot takes new work immediately
        v2 = server.try_submit(_prompt(), 4, deadline_s=100.0)
        server.step()
        assert v2.request.state == "running"

    def test_env_deadline_budget_applies(self, monkeypatch):
        monkeypatch.setenv("DL4J_SERVE_DEADLINE_S", "2.5")
        server = _server()
        t = {"now": 100.0}
        server.clock = lambda: t["now"]
        v = server.try_submit(_prompt(), 4)
        assert v.admitted
        assert v.request.deadline_s == pytest.approx(102.5)

    def test_shed_events_on_tracer_timeline(self):
        tr = SpanTracer()
        set_tracer(tr)
        try:
            server = _server()
            t = {"now": 10.0}
            server.clock = lambda: t["now"]
            server.try_submit(_prompt(), 4, deadline_s=1.0)
            evs = [sp for sp in tr.spans() if sp.name == "serve.shed"]
            assert len(evs) == 1
            assert evs[0].attrs["reason"] == "deadline"
        finally:
            set_tracer(None)


# ---------------------------------------------------------------------------
# criticality displacement
# ---------------------------------------------------------------------------
class TestCriticalityDisplacement:
    def test_queue_pops_by_class_priority(self):
        q = RequestQueue(max_depth=4)
        reqs = [ServeRequest(prompt=_prompt(), max_new_tokens=4,
                             criticality=c)
                for c in ("batch", "best_effort", "interactive")]
        for r in reqs:
            assert q.try_push(r)
        assert q.pop() is reqs[2]           # interactive first
        assert q.pop() is reqs[0]           # then batch
        assert q.pop() is reqs[1]           # best_effort last

    def test_displace_sheds_costliest_of_lowest_class(self):
        q = RequestQueue(max_depth=2)
        cheap = ServeRequest(prompt=_prompt(2), max_new_tokens=2,
                             criticality="best_effort")
        costly = ServeRequest(prompt=_prompt(8), max_new_tokens=16,
                              criticality="best_effort")
        for r in (cheap, costly):
            assert q.try_push(r)
        newcomer = ServeRequest(prompt=_prompt(), max_new_tokens=4,
                                criticality="batch")
        admitted, victim = q.displace(newcomer)
        assert admitted and victim is costly

    def test_same_class_never_displaced(self):
        q = RequestQueue(max_depth=1)
        assert q.try_push(ServeRequest(prompt=_prompt(),
                                       max_new_tokens=4,
                                       criticality="batch"))
        admitted, victim = q.displace(
            ServeRequest(prompt=_prompt(), max_new_tokens=4,
                         criticality="batch"))
        assert not admitted and victim is None

    def test_server_displacement_evidence(self):
        server = _server(slots=1, max_queue=1)
        server.try_submit(_prompt(), 8, criticality="interactive")
        server.step()                       # slot taken
        vb = server.try_submit(_prompt(5), 4, criticality="batch")
        assert vb.admitted                  # fills the queue
        vi = server.try_submit(_prompt(6), 4, criticality="interactive")
        assert vi.admitted and vi.displaced is vb.request
        assert vb.request.state == "shed"
        assert vb.request.shed_reason == "shed_overload"
        decision = server.shed_log[-1]
        assert decision["reason"] == "shed_overload"
        assert decision["displaced_by"] == vi.request.id
        assert server.stats()["shed_by_class"] == {"batch": 1}

    def test_interactive_overload_sheds_newcomer_not_peer(self):
        server = _server(slots=1, max_queue=1)
        server.try_submit(_prompt(), 8, criticality="interactive")
        server.step()
        assert server.try_submit(_prompt(), 4,
                                 criticality="interactive").admitted
        v = server.try_submit(_prompt(), 4, criticality="interactive")
        assert not v.admitted and v.reason == "queue_full"
        assert server.stats()["shed"] == 0


# ---------------------------------------------------------------------------
# retry budget
# ---------------------------------------------------------------------------
class TestRetryBudget:
    def test_token_bucket_arithmetic(self):
        b = RetryBudget(ratio=0.5, burst=2.0)
        assert b.remaining("batch") == 2.0
        assert b.try_spend("batch") and b.try_spend("batch")
        assert not b.try_spend("batch")     # dry
        b.deposit("batch")
        assert b.remaining("batch") == pytest.approx(0.5)
        assert not b.has("batch")           # 0.5 < 1 token
        b.refund("batch", 5.0)
        assert b.remaining("batch") == 2.0  # capped at burst

    def test_classes_are_independent(self):
        b = RetryBudget(ratio=0.1, burst=1.0)
        assert b.try_spend("interactive")
        assert not b.has("interactive")
        assert b.has("batch")

    def test_unknown_class_rejected(self):
        b = RetryBudget()
        with pytest.raises(ValueError):
            b.deposit("platinum")

    def test_dry_budget_parks_failover_with_evidence(self):
        reps = [_replica(f"r{i}", fuse_steps=2) for i in range(2)]
        router = FleetRouter(reps)
        router.retry_budget = RetryBudget(ratio=0.0, burst=0.0)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        frs = [router.submit(_prompt(), 4, seed=i) for i in range(2)]
        victim_rid = frs[0].replica_id
        victims = [fr for fr in frs if fr.replica_id == victim_rid]
        before = metrics().counter("serve_retry_denied_total").value(
            kind="failover", criticality="interactive")
        controller.evict(victim_rid, reason="test")
        # the re-dispatch was denied: parked, not placed, one evidence
        # record per request
        assert all(fr.replica_id is None for fr in victims)
        assert len(router._pending) == len(victims)
        assert metrics().counter("serve_retry_denied_total").value(
            kind="failover", criticality="interactive") \
            == before + len(victims)
        # funding the bucket lets the parked work place on the next tick
        router.retry_budget = RetryBudget(ratio=0.1, burst=10.0)
        assert router.retry_pending() == len(victims)
        survivor = [r for r in reps if r.alive][0]
        lm = _lm()
        while router.unfinished():
            survivor.step_once()
        for fr in frs:
            assert np.array_equal(fr.output,
                                  _ref(lm, fr.prompt, fr.max_new_tokens))

    def test_first_placement_is_free(self):
        reps = [_replica("r0", fuse_steps=2)]
        router = FleetRouter(reps)
        router.retry_budget = RetryBudget(ratio=0.0, burst=0.0)
        fr = router.try_submit(_prompt(), 4)
        assert fr is not None and fr.replica_id == "r0"


# ---------------------------------------------------------------------------
# hedging
# ---------------------------------------------------------------------------
class TestHedging:
    def _fleet(self, t):
        reps = [_replica(f"r{i}", slots=1, max_queue=2, fuse_steps=2)
                for i in range(2)]
        clock = lambda: t["now"]  # noqa: E731
        router = FleetRouter(reps, clock=clock)
        for r in reps:
            r.clock = clock
            r.server.clock = clock
        return reps, router

    def test_hedge_placed_after_threshold_and_budget_gated(self):
        t = {"now": 0.0}
        reps, router = self._fleet(t)
        router.hedge_after_s = 0.05
        # r0 and r1 each get a slot-filling request
        a = router.submit(_prompt(), 8, seed=0)
        b = router.submit(_prompt(5), 8, seed=0)
        for r in reps:
            r.step_once()
        # c queues behind one of them
        c = router.submit(_prompt(6), 4, seed=0)
        assert c.inner.state == "queued"
        assert router.maybe_hedge() == 0    # not past threshold yet
        t["now"] = 0.1
        assert router.maybe_hedge() == 1
        assert c.hedge is not None
        assert c.hedge_replica_id != c.replica_id
        assert len(router.hedge_log) == 1
        # a dry budget refuses further hedging
        router.retry_budget = RetryBudget(ratio=0.0, burst=0.0)
        c.hedge = None                      # pretend it never hedged
        c.hedge_replica_id = None
        assert router.maybe_hedge() == 0
        assert a is not None and b is not None

    def test_hedge_win_cancels_queued_primary(self):
        t = {"now": 0.0}
        reps, router = self._fleet(t)
        router.hedge_after_s = 0.05
        lm = _lm()
        a = router.submit(_prompt(), 2, seed=0)    # r0, short
        b = router.submit(_prompt(5), 8, seed=0)   # r1, long
        for r in reps:
            r.step_once()
        c = router.submit(_prompt(6), 4, seed=0)   # queued (on r0)
        primary_rid = c.replica_id
        t["now"] = 0.1
        assert router.maybe_hedge() == 1
        hedge_rep = router._by_id[c.hedge_replica_id]
        # the hedge's replica finishes its current stream, then starts
        # the hedge copy; the primary copy is STILL queued
        finish_first = a if hedge_rep.replica_id == "r0" else b
        while not finish_first.finished:
            hedge_rep.step_once()
        hedge_rep.step_once()
        assert c.hedge.state in ("running", "finished")
        assert c.inner.state == "queued"
        router.maybe_hedge()                # reconcile: hedge wins
        assert router.hedge_wins == 1
        assert c.replica_id == hedge_rep.replica_id
        assert c.hedge is None
        # the canceled primary no longer holds a seat on its old replica
        assert all(
            q is not c.inner
            for q in [router._by_id[primary_rid].server.queue.pop()])
        while not c.finished:
            hedge_rep.step_once()
        assert np.array_equal(c.output, _ref(lm, c.prompt, 4))

    def test_primary_win_cancels_hedge(self):
        t = {"now": 0.0}
        reps, router = self._fleet(t)
        router.hedge_after_s = 0.05
        router.submit(_prompt(), 8, seed=0)        # r0 busy
        router.submit(_prompt(5), 8, seed=0)       # r1 busy
        for r in reps:
            r.step_once()
        c = router.submit(_prompt(6), 4, seed=0)
        t["now"] = 0.1
        assert router.maybe_hedge() == 1
        hedge_req = c.hedge
        # the PRIMARY's replica frees first and starts c
        pri_rep = router._by_id[c.replica_id]
        while c.inner.state == "queued":
            pri_rep.step_once()
        router.maybe_hedge()                # reconcile: primary wins
        assert c.hedge is None and hedge_req.canceled
        assert router.hedge_wins == 0

    def test_sampled_fleet_refuses_hedging(self):
        lm = _lm("sampled", seed=4)
        reps = [ServeReplica(f"r{i}", lm, slots=1, max_len=64,
                             temperature=0.8) for i in range(2)]
        router = FleetRouter(reps)
        router.hedge_after_s = 0.0
        router.submit(_prompt(), 4, seed=7)
        assert router.maybe_hedge() == 0


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------
class TestDrain:
    def test_drain_migrates_live_and_queued_zero_recompute(self):
        lm = _lm()
        reps = [_replica(f"r{i}", slots=2, max_queue=4, fuse_steps=2)
                for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        # 3 requests: two fill r0's slots, one queues behind them
        # (affinity pins them all to r0)
        frs = [router.submit(_prompt(4 + i), 8, seed=i, affinity="pin")
               for i in range(3)]
        assert all(fr.replica_id == "r0" for fr in frs)
        r0 = router._by_id["r0"]
        for _ in range(2):
            r0.step_once()                  # both live streams mid-flight
        live_tokens = {fr.id: list(fr.tokens) for fr in frs}
        assert any(live_tokens.values())    # some tokens already emitted
        failover_before = metrics().counter(
            "fleet_serve_failover_requests_total").value()
        decision = controller.drain("r0", reason="test-drain")
        # evidence + bookkeeping
        assert controller.drained == ["r0"]
        assert r0.retired and r0.alive is False and not r0.dead
        assert decision["migrated"] == 3
        assert decision["fallback_failovers"] == 0
        assert decision["live"] == 2 and decision["queued"] == 1
        assert controller.drain_log[-1] is decision
        # drain is NOT failover: the failover counter did not move
        assert metrics().counter(
            "fleet_serve_failover_requests_total").value() \
            == failover_before
        # already-emitted tokens were carried, not recomputed
        for fr in frs:
            assert list(fr.tokens)[:len(live_tokens[fr.id])] \
                == live_tokens[fr.id]
        r1 = router._by_id["r1"]
        while router.unfinished():
            r1.step_once()
        for fr in frs:
            assert np.array_equal(fr.output,
                                  _ref(lm, fr.prompt, fr.max_new_tokens))

    def test_drain_drops_hedge_copies_not_primaries(self):
        t = {"now": 0.0}
        reps = [_replica(f"r{i}", slots=1, max_queue=2, fuse_steps=2)
                for i in range(2)]
        clock = lambda: t["now"]  # noqa: E731
        router = FleetRouter(reps, clock=clock)
        for r in reps:
            r.clock = clock
            r.server.clock = clock
        router.hedge_after_s = 0.05
        controller = FleetController(router, None, evict_timeout_s=5.0,
                                     clock=clock)
        router.submit(_prompt(), 8, seed=0)
        router.submit(_prompt(5), 8, seed=0)
        for r in reps:
            r.step_once()
        c = router.submit(_prompt(6), 4, seed=0)
        t["now"] = 0.1
        assert router.maybe_hedge() == 1
        hedge_rid = c.hedge_replica_id
        decision = controller.drain(hedge_rid, reason="test")
        assert decision["dropped_hedges"] == 1
        assert c.hedge is None
        assert not c.finished and c.shed_reason is None

    def test_drain_is_idempotent_and_skips_evicted(self):
        reps = [_replica(f"r{i}") for i in range(2)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=5.0)
        controller.drain("r0")
        assert controller.drain("r0")["reason"] == "already_evicted"
        controller.evict("r1", reason="dead")
        assert controller.drain("r1")["reason"] == "already_evicted"

    def test_drain_emits_flight_evidence(self):
        tr = SpanTracer()
        set_tracer(tr)
        try:
            reps = [_replica(f"r{i}") for i in range(2)]
            router = FleetRouter(reps)
            controller = FleetController(router, None,
                                         evict_timeout_s=5.0)
            router.submit(_prompt(), 4)
            controller.drain("r0")
            evs = [sp for sp in tr.spans() if sp.name == "serve.drain"]
            assert len(evs) == 1
            assert evs[0].attrs["replica"] == "r0"
        finally:
            set_tracer(None)


# ---------------------------------------------------------------------------
# the acceptance soak: 3x-capacity storm + mid-storm drain
# ---------------------------------------------------------------------------
class TestOverloadSoak:
    """Seeded virtual-clock storm at ~3x fleet capacity with a
    criticality mix, per-class deadlines, and a mid-storm graceful
    drain — the ``--serve-slo`` gate's assertions, read mechanically
    off the run report and the decision logs."""

    PIN = 0.01                              # pinned per-step cost

    def _fleet(self):
        reps = [_replica(f"r{i}", slots=2, max_queue=4, fuse_steps=2)
                for i in range(3)]
        router = FleetRouter(reps)
        controller = FleetController(router, None, evict_timeout_s=50.0)

        def pinned_timer(replica):
            replica.step_once()
            return self.PIN

        return router, controller, FleetLoadDriver(
            router, controller, step_timer=pinned_timer)

    def test_storm_soak_slos(self):
        lm = _lm()
        # uncontended baseline: same fleet shape, gentle all-interactive
        # trickle — the TTFT yardstick
        _, _, base_driver = self._fleet()
        base_sched = poisson_schedule(
            30, rate_rps=20.0, vocab_size=61, prompt_lens=(4, 8),
            max_new_tokens=(6,), deadlines_s={"interactive": 10.0},
            seed=11)
        base = base_driver.run(base_sched).summary()
        assert base["finished"] == 30
        # uncontended TTFT on a virtual clock can round to zero (the
        # token lands in the same tick the request arrives); the
        # physical floor is one pinned step
        base_ttft = max(base["ttft_p50_ms_by_class"]["interactive"],
                        1000.0 * self.PIN)

        # the storm: ~3x capacity. Capacity ~ 3 replicas x 2 slots x
        # (2 fused tokens / 0.01 s) / ~7 tokens-per-request ~ 170 rps;
        # drive 500 rps with a 25/60/15 class mix and per-class
        # deadline budgets wide enough that interactive holds
        router, controller, driver = self._fleet()
        sched = poisson_schedule(
            200, rate_rps=500.0, vocab_size=61, prompt_lens=(4, 8),
            max_new_tokens=(6,),
            criticality_mix={"interactive": 0.20, "batch": 0.65,
                             "best_effort": 0.15},
            deadlines_s={"interactive": 2.0, "batch": 0.15,
                         "best_effort": 0.08},
            seed=12)
        storm_len_s = sched[-1].arrival_s
        failover_before = metrics().counter(
            "fleet_serve_failover_requests_total").value()
        report = driver.run(sched, drain_at_s=storm_len_s / 2,
                            drain_replica="r0")
        s = report.summary()

        # --- the storm actually stormed, and deadlines actually fired
        assert s["shed"] + s["rejected"] > 0, s
        assert s["finished"] > 0
        assert s["expired_in_queue"] + s["expired_in_flight"] > 0, s

        # --- SLO 1: interactive p50 TTFT within 2x uncontended
        storm_ttft = s["ttft_p50_ms_by_class"]["interactive"]
        assert storm_ttft <= 2.0 * base_ttft, (storm_ttft, base_ttft)

        # --- SLO 2: every shed was batch/best_effort OR past-deadline
        decisions = list(router.shed_log)
        for r in router.replicas:
            decisions.extend(r.server.shed_log)
        assert decisions
        for d in decisions:
            assert (d["criticality"] in ("batch", "best_effort")
                    or d["reason"] == "deadline"), d

        # --- SLO 3: retry amplification bounded
        assert s["retry_amplification"] is not None
        assert s["retry_amplification"] <= 1.2, s["retry_amplification"]

        # --- SLO 4: the mid-storm drain retired r0 gracefully
        assert controller.drained == ["r0"]
        assert router._by_id["r0"].retired
        assert driver.drain_summary is not None
        assert driver.drain_summary["fallback_failovers"] == 0
        # zero recompute: the failover path never fired
        assert metrics().counter(
            "fleet_serve_failover_requests_total").value() \
            == failover_before

        # --- SLO 5: zero lost tokens — every finished stream is
        # token-identical to the uncontended reference (greedy fleet)
        finished = [fr for fr in router.requests if fr.finished]
        assert finished
        for fr in finished:
            assert np.array_equal(
                fr.output, _ref(lm, fr.prompt, fr.max_new_tokens)), fr.id

        # --- bookkeeping coherence: every submitted request ended in
        # exactly one terminal ledger column
        assert s["submitted"] == len(router.requests)
        states = [fr.state for fr in router.requests]
        assert s["finished"] + s["shed"] \
            + sum(1 for st in states
                  if st not in ("finished", "shed")) \
            == s["submitted"]
        # expiry split is consistent with the per-server evidence
        assert s["expired_in_queue"] + s["expired_in_flight"] \
            <= s["shed"] + len(router.shed_log)
