"""Online serving subsystem: batched slot decode vs single-request
``generate`` equivalence, continuous batching, compile flatness, the
prompt-length ladder, the persisted compilation cache, the Poisson load
generator, and the direction-aware bench regression gate.

The load-bearing claims:

1. A slot's token sequence is IDENTICAL to ``TransformerLM.generate``
   on the same prompt — greedy and sampled (per-slot RNG replays the
   single-request ``split`` chain) — across learned/RoPE positions, GQA,
   sliding windows, bucket padding, and slot recycling.
2. The server compiles one decode program per slot count and one
   prefill per prompt-ladder rung, and a ragged stream adds ZERO
   programs after warmup.
3. ``generate_beam(beam_size=1)`` is greedy ``generate``.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.monitor import metrics, set_tracer, SpanTracer
from deeplearning4j_tpu.perf.bucketing import (
    DEFAULT_PROMPT_BUCKETS, pad_prompt, prompt_bucket)
from deeplearning4j_tpu.serving import (
    DecodeServer, ServeQueueFull, SlotKVCache, compile_cache_stats,
    ensure_compile_cache, kv_pool_nbytes, max_slots_in_budget,
    poisson_schedule, run_open_loop, serve_draft_layers,
    serve_fuse_steps, serve_max_queue, serve_slots)
from deeplearning4j_tpu.serving import compile_cache as compile_cache_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench_report():
    spec = importlib.util.spec_from_file_location(
        "bench_report_serving", os.path.join(REPO, "scripts",
                                             "bench_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_report = _load_bench_report()


def _lm(pos_encoding="learned", **kw):
    cfg = dict(vocab_size=61, d_model=32, num_heads=4, num_kv_heads=2,
               num_layers=2, max_len=96, seed=3,
               pos_encoding=pos_encoding)
    cfg.update(kw)
    return TransformerLM(**cfg).init()


def _prompts(rng, lens, vocab=61):
    return [rng.integers(1, vocab, n).astype(np.int32) for n in lens]


class FakeClock:
    """Monotonic fake: every read advances ``tick`` so durations are
    nonzero and deterministic; ``sleep`` jumps the idle gaps."""

    def __init__(self, tick=0.01):
        self.t = 0.0
        self.tick = tick

    def __call__(self):
        self.t += self.tick
        return self.t

    def sleep(self, s):
        self.t += s


# ---------------------------------------------------------------------------
# prompt-length ladder (perf/bucketing.py satellite)
# ---------------------------------------------------------------------------
class TestPromptLadder:
    def test_rungs_are_smallest_upper_bound(self):
        assert prompt_bucket(1) == 16
        assert prompt_bucket(16) == 16
        assert prompt_bucket(17) == 32
        assert prompt_bucket(100) == 128

    def test_max_len_caps_the_rung(self):
        # 100 -> 128 would overflow a 120-slot pool: cap at max_len
        assert prompt_bucket(100, max_len=120) == 120
        assert prompt_bucket(100, max_len=4096) == 128

    def test_invalid_lengths_raise(self):
        with pytest.raises(ValueError):
            prompt_bucket(0)
        with pytest.raises(ValueError):
            prompt_bucket(130, max_len=120)

    def test_disable_flag_makes_prompts_exact(self, monkeypatch):
        monkeypatch.setenv("DL4J_DISABLE_BUCKETING", "1")
        assert prompt_bucket(13) == 13

    def test_pad_prompt_roundtrip(self):
        p = np.arange(1, 6, dtype=np.int32)
        padded, n = pad_prompt(p, 16)
        assert n == 5
        assert padded.shape == (16,)
        assert padded.dtype == np.int32
        assert np.array_equal(padded[:5], p)
        assert not padded[5:].any()

    def test_pad_prompt_batched_and_overflow(self):
        p = np.ones((2, 7), np.int32)
        padded, n = pad_prompt(p, 8)
        assert padded.shape == (2, 8) and n == 7
        with pytest.raises(ValueError):
            pad_prompt(np.ones(9, np.int32), 8)

    def test_ladder_stays_off_training_eval_paths(self):
        # the serving ladder is a separate constant: the batch ladder
        # the eval path uses must not silently grow prompt rungs
        from deeplearning4j_tpu.perf.bucketing import DEFAULT_BATCH_BUCKETS
        assert DEFAULT_PROMPT_BUCKETS != DEFAULT_BATCH_BUCKETS


# ---------------------------------------------------------------------------
# equivalence: batched slot decode vs single-request generate
# ---------------------------------------------------------------------------
class TestDecodeEquivalence:
    @pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
    def test_greedy_matches_generate(self, rng, pos_encoding):
        """Three concurrent requests at ragged prompt/generation lengths
        through 2 slots (forces recycling) — token-for-token identical
        to the per-request ``generate`` programs."""
        lm = _lm(pos_encoding)
        prompts = _prompts(rng, (5, 11, 23))
        max_new = [7, 4, 9]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96)
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert np.array_equal(req.output, ref)

    def test_sampled_matches_generate_per_slot_rng(self, rng):
        """Each slot's RNG stream replays the single-request
        ``sample``/``split`` chain: serving with ``seed=s`` emits the
        same tokens as ``generate(..., seed=s)``."""
        lm = _lm(num_kv_heads=4)  # H == Hkv: the dense-attention path
        prompts = _prompts(rng, (5, 11))
        refs = [np.asarray(lm.generate(
            p[None], 6, temperature=0.7, top_k=13, seed=s))[0]
            for s, p in enumerate(prompts)]
        srv = DecodeServer(lm, slots=2, max_len=96, temperature=0.7,
                           top_k=13)
        reqs = [srv.submit(p, 6, seed=s) for s, p in enumerate(prompts)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)

    def test_sliding_window_matches_generate(self, rng):
        lm = _lm("rope", attn_window=8)
        p = _prompts(rng, (13,))[0]
        ref = np.asarray(lm.generate(p[None], 10))[0]
        srv = DecodeServer(lm, slots=3, max_len=64)
        req = srv.submit(p, 10)
        srv.drain()
        assert np.array_equal(req.output, ref)

    def test_slot_recycling_preserves_tokens(self, rng):
        """6 requests through 2 slots: retired slots' stale K/V must be
        unreachable for their successors (the mask-correctness claim of
        the slot lifecycle)."""
        lm = _lm()
        prompts = _prompts(rng, (3, 9, 17, 5, 21, 7))
        max_new = [5, 2, 6, 8, 3, 4]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96)
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)

    def test_bucket_padding_is_mask_correct(self, rng, monkeypatch):
        """The same prompt served bucket-padded and exact produces the
        same tokens — the pad tail is causally unreachable."""
        lm = _lm("rope")
        p = _prompts(rng, (9,))[0]
        srv = DecodeServer(lm, slots=1, max_len=96)  # pads 9 -> 16
        req = srv.submit(p, 8)
        srv.drain()
        monkeypatch.setenv("DL4J_DISABLE_BUCKETING", "1")
        exact = DecodeServer(lm, slots=1, max_len=96)  # compiles at 9
        req2 = exact.submit(p, 8)
        exact.drain()
        assert exact.engine.compile_counts()["prefill_buckets"] == [9]
        assert np.array_equal(req.output, req2.output)

    def test_max_new_tokens_one_needs_no_decode_step(self, rng):
        lm = _lm()
        p = _prompts(rng, (6,))[0]
        ref = np.asarray(lm.generate(p[None], 1))[0]
        srv = DecodeServer(lm, slots=2, max_len=96)
        req = srv.submit(p, 1)
        srv.drain()
        assert np.array_equal(req.output, ref)
        assert srv.steps == 0  # retired at admission, no decode dispatch

    def test_beam_size_one_is_greedy_generate(self, rng):
        lm = _lm()
        prompt = np.stack(_prompts(rng, (7, 7)))
        greedy = np.asarray(lm.generate(prompt, 6))
        seqs, scores = lm.generate_beam(prompt, 6, beam_size=1)
        assert np.asarray(seqs).shape == (2, 1, 13)
        assert np.array_equal(np.asarray(seqs)[:, 0], greedy)


# ---------------------------------------------------------------------------
# continuous batching mechanics
# ---------------------------------------------------------------------------
class TestContinuousBatching:
    def test_compile_count_flat_after_warmup(self, rng):
        """A second ragged wave over the same ladder rungs adds ZERO
        programs — the acceptance invariant the bench asserts on-chip."""
        lm = _lm()
        srv = DecodeServer(lm, slots=3, max_len=96)
        before = metrics().counter("serve_program_builds_total").value(
            kind="prefill")
        for p, m in zip(_prompts(rng, (5, 12, 30)), (4, 3, 5)):
            srv.submit(p, m)
        srv.drain()
        warm = srv.engine.program_builds
        assert srv.engine.compile_counts() == {
            "decode": 1, "prefill_buckets": [16, 32], "total": 3}
        assert metrics().counter("serve_program_builds_total").value(
            kind="prefill") == before + 2
        # steady state: same rung menu, different lengths/counts
        for p, m in zip(_prompts(rng, (7, 16, 25, 9)), (2, 5, 3, 4)):
            srv.submit(p, m)
        srv.drain()
        assert srv.engine.program_builds == warm
        assert len(srv.finished) == 7

    def test_queue_bound_rejects_with_backpressure(self, rng):
        lm = _lm()
        srv = DecodeServer(lm, slots=1, max_queue=2, max_len=96)
        reg = metrics()
        rejected0 = reg.counter("serve_requests_total").value(
            event="rejected")
        srv.submit(_prompts(rng, (4,))[0], 3)
        srv.submit(_prompts(rng, (4,))[0], 3)
        with pytest.raises(ServeQueueFull):
            srv.submit(_prompts(rng, (4,))[0], 3)
        assert reg.counter("serve_requests_total").value(
            event="rejected") == rejected0 + 1
        srv.drain()
        assert len(srv.finished) == 2

    def test_submit_validation(self, rng):
        lm = _lm()
        srv = DecodeServer(lm, slots=1, max_len=32)
        with pytest.raises(ValueError):
            srv.submit(np.empty(0, np.int32), 4)
        with pytest.raises(ValueError):
            srv.submit(_prompts(rng, (4,))[0], 0)
        with pytest.raises(ValueError):
            srv.submit(_prompts(rng, (30,))[0], 4)  # 34 > max_len

    def test_slot_capacity_validation(self):
        lm = _lm("learned")
        with pytest.raises(ValueError):
            SlotKVCache(lm, slots=0)
        with pytest.raises(ValueError):
            # learned table bounds the slot capacity the way it bounds
            # generate(); rope does not (second construction succeeds)
            SlotKVCache(lm, slots=2, max_len=200)
        rope = _lm("rope")
        assert SlotKVCache(rope, slots=2, max_len=200).max_len == 200

    def test_metrics_and_spans(self, rng):
        """TTFT/latency histograms, token counters, occupancy gauge,
        and the serve.step/serve.prefill spans all record."""
        lm = _lm()
        tr = SpanTracer()
        set_tracer(tr)
        try:
            reg = metrics()
            ttft0 = reg.histogram("serve_ttft_seconds").value()["count"]
            lat0 = reg.histogram(
                "serve_request_latency_seconds").value()["count"]
            tok0 = reg.counter("serve_tokens_total").value()
            srv = DecodeServer(lm, slots=2, max_len=96)
            reqs = [srv.submit(p, 4) for p in _prompts(rng, (5, 9))]
            srv.drain()
            assert all(r.ttft_s is not None and r.ttft_s >= 0
                       for r in reqs)
            assert all(r.latency_s is not None and r.latency_s >= 0
                       for r in reqs)
            assert reg.histogram("serve_ttft_seconds").value(
                )["count"] == ttft0 + 2
            assert reg.histogram("serve_request_latency_seconds").value(
                )["count"] == lat0 + 2
            assert reg.counter("serve_tokens_total").value() == tok0 + 8
            assert reg.gauge("serve_slot_occupancy").value() == 0.0
            names = {sp.name for sp in tr.spans()}
            assert {"serve.step", "serve.prefill"} <= names
            prefills = [sp for sp in tr.spans()
                        if sp.name == "serve.prefill"]
            assert {sp.attrs["prompt_len"] for sp in prefills} == {5, 9}
        finally:
            set_tracer(None)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("DL4J_SERVE_SLOTS", "5")
        monkeypatch.setenv("DL4J_SERVE_MAX_QUEUE", "11")
        assert serve_slots() == 5
        assert serve_max_queue() == 11
        monkeypatch.setenv("DL4J_SERVE_SLOTS", "bogus")
        assert serve_slots() == 8
        monkeypatch.delenv("DL4J_SERVE_SLOTS")
        monkeypatch.delenv("DL4J_SERVE_MAX_QUEUE")
        assert serve_slots() == 8
        assert serve_max_queue() == 64


# ---------------------------------------------------------------------------
# persisted XLA compilation cache
# ---------------------------------------------------------------------------
class TestCompileCache:
    def test_lazy_configuration(self, tmp_path, monkeypatch):
        prev = jax.config.jax_compilation_cache_dir
        d = str(tmp_path / "xla-cache")
        monkeypatch.setenv("DL4J_COMPILE_CACHE_DIR", d)
        compile_cache_mod._reset_for_tests()
        try:
            assert ensure_compile_cache() == d
            assert jax.config.jax_compilation_cache_dir == d
            assert os.path.isdir(d)
            stats = compile_cache_stats()
            assert stats["dir"] == d and stats["configured"]
            # idempotent: second call is a no-op, same answer
            assert ensure_compile_cache() == d
        finally:
            jax.config.update("jax_compilation_cache_dir", prev)
            compile_cache_mod._reset_for_tests()

    def test_unset_env_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("DL4J_COMPILE_CACHE_DIR", raising=False)
        compile_cache_mod._reset_for_tests()
        assert ensure_compile_cache() is None
        assert compile_cache_stats() == {
            "dir": None, "configured": False, "entries": 0, "bytes": 0}


# ---------------------------------------------------------------------------
# Poisson open-loop load generator
# ---------------------------------------------------------------------------
class TestLoadGenerator:
    def test_schedule_is_deterministic_and_ragged(self):
        a = poisson_schedule(20, 50.0, vocab_size=61, seed=7)
        b = poisson_schedule(20, 50.0, vocab_size=61, seed=7)
        assert len(a) == 20
        assert all(x.arrival_s <= y.arrival_s for x, y in zip(a, a[1:]))
        assert {x.prompt.shape[0] for x in a} > {a[0].prompt.shape[0]}
        for x, y in zip(a, b):
            assert x.arrival_s == y.arrival_s
            assert np.array_equal(x.prompt, y.prompt)

    def test_open_loop_run_reports(self, rng):
        lm = _lm()
        clock = FakeClock()
        srv = DecodeServer(lm, slots=2, max_len=96, clock=clock)
        sched = poisson_schedule(
            8, 100.0, vocab_size=61, prompt_lens=(5, 9),
            max_new_tokens=(2, 4), seed=3)
        report = run_open_loop(srv, sched, clock=clock,
                               sleep=clock.sleep)
        s = report.summary()
        assert s["finished"] == 8 and s["rejected"] == 0
        assert s["tokens"] == sum(len(r.tokens) for r in srv.finished)
        assert s["p50_latency_ms"] > 0
        assert s["p99_latency_ms"] >= s["p50_latency_ms"]
        assert s["ttft_p50_ms"] > 0
        assert 0 < s["occupancy_mean"] <= 1
        assert s["tokens_per_sec"] > 0

    def test_open_loop_drops_on_overflow(self, rng):
        """Open loop means overflow drops — the stream must not turn
        into a closed loop behind the queue bound."""
        lm = _lm()
        clock = FakeClock(tick=0.001)
        srv = DecodeServer(lm, slots=1, max_queue=1, max_len=96,
                           clock=clock)
        # all arrivals at ~t=0: one runs, one queues, the rest reject
        sched = poisson_schedule(
            6, 1e6, vocab_size=61, prompt_lens=(5,),
            max_new_tokens=(6,), seed=0)
        report = run_open_loop(srv, sched, clock=clock,
                               sleep=clock.sleep)
        assert report.rejected > 0
        assert report.finished + report.rejected == 6
        assert report.finished == len(srv.finished)

    @pytest.mark.slow
    def test_soak_ragged_stream_never_recompiles(self, rng):
        """Soak: 60 ragged requests through 4 slots; after the first
        rung-covering wave the program count never moves, and every
        request finishes with exactly max_new tokens."""
        lm = _lm("rope")
        clock = FakeClock(tick=0.001)
        srv = DecodeServer(lm, slots=4, max_len=96, clock=clock)
        warm = poisson_schedule(
            8, 500.0, vocab_size=61, prompt_lens=(4, 12, 20, 40),
            max_new_tokens=(3, 6), seed=1)
        run_open_loop(srv, warm, clock=clock, sleep=clock.sleep)
        builds = srv.engine.program_builds
        soak = poisson_schedule(
            60, 500.0, vocab_size=61, prompt_lens=(4, 12, 20, 40),
            max_new_tokens=(3, 6), seed=2)
        report = run_open_loop(srv, soak, clock=clock, sleep=clock.sleep)
        assert srv.engine.program_builds == builds
        assert report.finished == 60
        for req in srv.finished:
            assert len(req.tokens) == req.max_new_tokens


# ---------------------------------------------------------------------------
# direction-aware bench regression gate (scripts/bench_report.py)
# ---------------------------------------------------------------------------
class TestBenchReportDirections:
    def test_latency_rise_is_a_regression(self):
        series = {"serve_p50_latency_ms": [(1, 100.0), (2, 150.0)]}
        out = bench_report.find_regressions(series, 20.0)
        assert len(out) == 1 and "above" in out[0]

    def test_latency_drop_is_an_improvement(self):
        series = {"serve_p99_latency_ms": [(1, 100.0), (2, 60.0)]}
        assert bench_report.find_regressions(series, 20.0) == []

    def test_throughput_direction_unchanged(self):
        assert bench_report.find_regressions(
            {"serve_tokens_per_sec": [(1, 100.0), (2, 70.0)]}, 20.0)
        assert not bench_report.find_regressions(
            {"serve_tokens_per_sec": [(1, 100.0), (2, 130.0)]}, 20.0)

    def test_lower_best_baseline_is_the_min(self):
        # r1's 80 is the best earlier point, not r2's 200: a 100 latest
        # is 25% above it -> regression even though it beats r2
        series = {"serve_p50_latency_ms": [(1, 80.0), (2, 200.0),
                                           (3, 100.0)]}
        out = bench_report.find_regressions(series, 20.0)
        assert len(out) == 1 and "r01" in out[0]

    def _write_round(self, path, n, serve):
        row = {"metric": "m", "value": 100.0, "unit": "u",
               "extras": {"serve": serve}}
        path.write_text(json.dumps({"n": n, "rc": 0, "parsed": row}))

    def test_end_to_end_gate_on_serve_section(self, tmp_path, capsys):
        a = tmp_path / "BENCH_r01.json"
        b = tmp_path / "BENCH_r02.json"
        self._write_round(a, 1, {"p50_latency_ms": 10.0,
                                 "p99_latency_ms": 20.0,
                                 "ttft_p50_ms": 5.0,
                                 "tokens_per_sec": 1000.0})
        self._write_round(b, 2, {"p50_latency_ms": 30.0,
                                 "p99_latency_ms": 21.0,
                                 "ttft_p50_ms": 5.0,
                                 "tokens_per_sec": 1000.0})
        rc = bench_report.main(["--check", str(a), str(b)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "serve_p50_latency_ms" in out
        assert "serve_p99_latency_ms" not in out  # 5% rise, under 20%

    def test_json_mode_carries_directions(self, tmp_path, capsys):
        a = tmp_path / "BENCH_r01.json"
        self._write_round(a, 1, {"p50_latency_ms": 10.0,
                                 "tokens_per_sec": 500.0})
        rc = bench_report.main(["--json", str(a)])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert payload["directions"]["serve_p50_latency_ms"] == "lower"
        assert payload["directions"]["serve_tokens_per_sec"] == "higher"
        row = payload["rounds"][0]
        assert row["serve_p50_latency_ms"] == 10.0


# ---------------------------------------------------------------------------
# fused multi-token decode: ("decode_fused", S, K)
# ---------------------------------------------------------------------------
class TestFusedDecode:
    @pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
    def test_fused_greedy_token_identical(self, rng, pos_encoding):
        """K=4 fused decode over 2 slots with recycling across fusion
        boundaries — token-for-token identical to the K=1 path (which
        PR 10 pinned to ``generate``)."""
        lm = _lm(pos_encoding)
        prompts = _prompts(rng, (5, 11, 23))
        max_new = [7, 4, 9]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96, fuse_steps=4)
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert req.state == "finished"
            assert np.array_equal(req.output, ref)

    def test_fused_dispatch_count_is_ceil(self, rng):
        """The acceptance invariant: one request generating N tokens at
        fuse_steps=K takes exactly ceil((N - prefill_token)/K) decode
        dispatches, counter-asserted."""
        lm = _lm()
        p = _prompts(rng, (6,))[0]
        for k, max_new in ((4, 10), (3, 10), (5, 6), (4, 5)):
            srv = DecodeServer(lm, slots=1, max_len=96, fuse_steps=k)
            reg = metrics()
            d0 = reg.counter("serve_decode_steps_total").value()
            req = srv.submit(p, max_new)
            srv.drain()
            want = -(-(max_new - 1) // k)   # ceil; 1 token from prefill
            assert srv.steps == want, (k, max_new, srv.steps)
            assert reg.counter("serve_decode_steps_total").value() \
                == d0 + want
            assert np.array_equal(
                req.output, np.asarray(lm.generate(p[None], max_new))[0])

    def test_fused_sampled_matches_single_step(self, rng):
        """Per-slot RNG splits move in-program: the K=3 fused stream
        emits the same sampled tokens as ``generate(seed=s)``."""
        lm = _lm(num_kv_heads=4)
        prompts = _prompts(rng, (5, 11))
        refs = [np.asarray(lm.generate(
            p[None], 6, temperature=0.7, top_k=13, seed=s))[0]
            for s, p in enumerate(prompts)]
        srv = DecodeServer(lm, slots=2, max_len=96, fuse_steps=3,
                           temperature=0.7, top_k=13)
        reqs = [srv.submit(p, 6, seed=s) for s, p in enumerate(prompts)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)

    def test_ragged_retirement_mid_scan(self, rng):
        """A short request (2 tokens) rides a K=4 scan beside a long one
        (9): the short slot self-freezes mid-scan (its remaining hits 0)
        and both streams stay token-exact through the recycle that
        follows."""
        lm = _lm()
        prompts = _prompts(rng, (4, 8, 6))
        max_new = [2, 9, 5]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96, fuse_steps=4)
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert len(req.tokens) == req.max_new_tokens
            assert np.array_equal(req.output, ref)

    def test_fuse_steps_one_is_pr10_bitwise(self, rng):
        """``DL4J_SERVE_FUSE_STEPS=1`` (the default) runs the identical
        PR-10 single-step program — same ("decode", S) cache key, same
        per-step dispatch cadence, same tokens."""
        lm = _lm()
        prompts = _prompts(rng, (5, 11))
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, (6, 4))]
        srv = DecodeServer(lm, slots=2, max_len=96)
        assert srv.fuse_steps == 1
        reqs = [srv.submit(p, m) for p, m in zip(prompts, (6, 4))]
        srv.drain()
        assert ("decode", 2) in srv.engine._programs
        assert not any(s[0] in ("decode_fused", "decode_spec")
                       for s in srv.engine._programs)
        assert srv.steps == 5   # max(6,4)-1: one dispatch per token
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)
        assert srv.stats()["tokens_per_slot_dispatch"] == 1.0

    def test_fused_env_flag(self, rng, monkeypatch):
        monkeypatch.setenv("DL4J_SERVE_FUSE_STEPS", "4")
        assert serve_fuse_steps() == 4
        lm = _lm()
        srv = DecodeServer(lm, slots=1, max_len=96)
        assert srv.fuse_steps == 4
        monkeypatch.setenv("DL4J_SERVE_FUSE_STEPS", "bogus")
        assert serve_fuse_steps() == 1
        monkeypatch.delenv("DL4J_SERVE_FUSE_STEPS")
        assert serve_fuse_steps() == 1

    def test_fused_compile_flat_after_warmup(self, rng):
        """The fused program joins the bounded program set: a second
        ragged wave at the same (S, K) adds ZERO programs."""
        lm = _lm()
        srv = DecodeServer(lm, slots=3, max_len=96, fuse_steps=4)
        for p, m in zip(_prompts(rng, (5, 12, 30)), (4, 3, 5)):
            srv.submit(p, m)
        srv.drain()
        warm = srv.engine.program_builds
        assert ("decode_fused", 3, 4) in srv.engine._programs
        for p, m in zip(_prompts(rng, (7, 16, 25, 9)), (2, 5, 3, 4)):
            srv.submit(p, m)
        srv.drain()
        assert srv.engine.program_builds == warm

    def test_admission_waits_for_fusion_boundary(self, rng):
        """With fuse_steps=K a request submitted while a dispatch is in
        flight joins at the next step() — the admission-boundary
        semantics (queue drains only through _admit)."""
        lm = _lm()
        srv = DecodeServer(lm, slots=2, max_len=96, fuse_steps=4)
        srv.submit(_prompts(rng, (5,))[0], 9)
        srv.step()                     # dispatch in flight for req 1
        late = srv.submit(_prompts(rng, (7,))[0], 3)
        assert late.state == "queued"  # mid-flight: not admitted
        srv.step()                     # boundary: admitted + decoded
        assert late.state in ("running", "finished")
        srv.drain()
        assert np.array_equal(
            late.output,
            np.asarray(lm.generate(late.prompt[None], 3))[0])


# ---------------------------------------------------------------------------
# quantized KV pool (DL4J_SERVE_KV_DTYPE)
# ---------------------------------------------------------------------------
class TestQuantizedKV:
    def test_int8_pool_shrinks_4x(self):
        lm = _lm()
        f32 = SlotKVCache(lm, slots=4, max_len=96, kv_dtype="float32")
        i8 = SlotKVCache(lm, slots=4, max_len=96, kv_dtype="int8")
        ratio = f32.per_slot_nbytes / i8.per_slot_nbytes
        assert 3.5 < ratio <= 4.0, ratio
        assert kv_pool_nbytes(lm, 4, 96, "int8") == i8.nbytes
        assert kv_pool_nbytes(lm, 4, 96, "float32") == f32.nbytes

    def test_validate_cache_budget_prices_the_quantized_pool(self):
        """PR 8's budget validator sees the pool + scale sidecars the
        runtime actually allocated: predicted nbytes == measured device
        bytes, and the int8 pool measures ~4x under float32."""
        from deeplearning4j_tpu.monitor.memory import validate_cache_budget
        lm = _lm()
        out = {}
        for dt in ("float32", "int8"):
            cache = SlotKVCache(lm, slots=4, max_len=96, kv_dtype=dt)
            v = validate_cache_budget(cache)
            assert v["within_tolerance"], v
            assert v["predicted_per_shard_bytes"] \
                == v["measured_per_device_bytes"] == cache.nbytes
            out[dt] = v["measured_per_device_bytes"]
        assert 3.5 < out["float32"] / out["int8"] <= 4.0

    def test_max_slots_in_budget_multiplies(self):
        lm = _lm()
        budget = 64 * 1024 * 1024
        n_f32 = max_slots_in_budget(lm, 96, budget, "float32")
        n_i8 = max_slots_in_budget(lm, 96, budget, "int8")
        assert n_i8 > 3 * n_f32
        assert max_slots_in_budget(lm, 96, 0, "int8") == 0

    def test_kv_dtype_validation_and_env(self, monkeypatch):
        lm = _lm()
        with pytest.raises(ValueError):
            SlotKVCache(lm, slots=1, kv_dtype="int4")
        monkeypatch.setenv("DL4J_SERVE_KV_DTYPE", "bf16")
        assert SlotKVCache(lm, slots=1).kv_dtype == "bfloat16"
        monkeypatch.delenv("DL4J_SERVE_KV_DTYPE")
        # unset: the pool stays in the model's compute dtype (the
        # pre-quantization default, bitwise)
        assert SlotKVCache(lm, slots=1).kv_dtype == "float32"

    def test_int8_greedy_token_parity(self, rng):
        """End-to-end: the int8-quantized pool reproduces the
        full-precision greedy stream on the small test model (pinned
        prompts — int8 is lossy by design; the logit-error test bounds
        how lossy)."""
        lm = _lm()
        prompts = _prompts(rng, (5, 17))
        max_new = [7, 6]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96, kv_dtype="int8")
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)
        assert srv.stats()["kv_dtype"] == "int8"

    def test_int8_fused_matches_single_step(self, rng):
        """Quantization composes with fusion: K=3 int8 == K=1 int8
        token-for-token (the requant/scatter sequence per slot is the
        same op chain either way)."""
        lm = _lm("rope")
        prompts = _prompts(rng, (3, 9, 17, 5))
        max_new = [5, 2, 6, 8]
        a = DecodeServer(lm, slots=2, max_len=96, kv_dtype="int8")
        b = DecodeServer(lm, slots=2, max_len=96, kv_dtype="int8",
                         fuse_steps=3)
        ra = [a.submit(p, m) for p, m in zip(prompts, max_new)]
        a.drain()
        rb = [b.submit(p, m) for p, m in zip(prompts, max_new)]
        b.drain()
        for x, y in zip(ra, rb):
            assert np.array_equal(x.output, y.output)

    def test_int8_roundtrip_logit_error_bound(self):
        """The quantization error contract: a dequantized K/V element
        sits within absmax/127 of the original (half a quantum after
        rounding), including after a requantizing scale growth."""
        import jax.numpy as jnp
        from deeplearning4j_tpu.serving.kv_cache import (
            dequant_slab, requant_write_slab)

        rng = np.random.default_rng(7)
        s_, t_, h_, d_ = 3, 8, 2, 4
        slab = jnp.zeros((s_, t_, h_, d_), jnp.int8)
        scale = jnp.zeros((s_, h_), jnp.float32)
        rows = jnp.arange(s_)
        vals1 = jnp.asarray(rng.normal(size=(s_, 4, h_, d_)), jnp.float32)
        pos1 = jnp.tile(jnp.arange(4)[None], (s_, 1))
        slab, scale = requant_write_slab(slab, scale, vals1, rows, pos1)
        # second write with LARGER values: forces a requantization of
        # the first write's entries under the grown scale
        vals2 = 3.0 * jnp.asarray(
            rng.normal(size=(s_, 4, h_, d_)), jnp.float32)
        pos2 = pos1 + 4
        slab, scale = requant_write_slab(slab, scale, vals2, rows, pos2)
        deq = np.asarray(dequant_slab(slab, scale, jnp.float32))
        bound = np.asarray(scale)[:, None, :, None] / 127.0 + 1e-7
        err1 = np.abs(deq[:, :4] - np.asarray(vals1))
        err2 = np.abs(deq[:, 4:] - np.asarray(vals2))
        # the requantized first write pays one extra rounding: 2 quanta
        assert (err1 <= 2 * bound).all(), err1.max()
        assert (err2 <= bound).all(), err2.max()


# ---------------------------------------------------------------------------
# speculative decoding (draft + verify inside the fused program)
# ---------------------------------------------------------------------------
class TestSpeculativeDecode:
    def test_full_self_draft_accepts_everything(self, rng):
        """draft_layers == num_layers makes the draft the target: every
        proposal verifies, tokens/slot-dispatch hits spec_tokens + 1,
        and the stream is the target's greedy stream."""
        lm = _lm()
        p = _prompts(rng, (5,))[0]
        srv = DecodeServer(lm, slots=1, max_len=96, draft_layers=2,
                           spec_tokens=3)
        req = srv.submit(p, 13)     # 12 decode tokens = 3 full rounds
        srv.drain()
        assert np.array_equal(
            req.output, np.asarray(lm.generate(p[None], 13))[0])
        st = srv.stats()
        assert st["spec_accept_rate"] == 1.0
        assert st["tokens_per_slot_dispatch"] == 4.0
        assert srv.steps == 3

    @pytest.mark.parametrize("pos_encoding", ["learned", "rope"])
    def test_shallow_draft_greedy_token_identity(self, rng, pos_encoding):
        """The speculative contract: whatever the draft proposes (here a
        1-of-2-layer self-draft with a low accept rate), the emitted
        stream is EXACTLY the target model's greedy stream — acceptance
        only changes how many dispatches it takes."""
        lm = _lm(pos_encoding)
        prompts = _prompts(rng, (5, 11, 23))
        max_new = [7, 4, 9]
        refs = [np.asarray(lm.generate(p[None], m))[0]
                for p, m in zip(prompts, max_new)]
        srv = DecodeServer(lm, slots=2, max_len=96, draft_layers=1,
                           spec_tokens=3)
        reqs = [srv.submit(p, m) for p, m in zip(prompts, max_new)]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)
        st = srv.stats()
        assert st["speculative"] and st["spec_proposed"] > 0

    def test_provided_draft_model(self, rng):
        """An independently seeded draft TransformerLM rides the same
        slot machinery (its own pool) and preserves target greedy
        token identity."""
        lm = _lm("rope")
        draft = _lm("rope", num_layers=1, seed=9)
        p = _prompts(rng, (9,))[0]
        ref = np.asarray(lm.generate(p[None], 8))[0]
        srv = DecodeServer(lm, slots=2, max_len=96, draft_model=draft,
                           spec_tokens=2)
        req = srv.submit(p, 8)
        srv.drain()
        assert np.array_equal(req.output, ref)

    def test_spec_composes_with_fuse_steps(self, rng):
        """K rounds per dispatch: fuse_steps=2 x spec_tokens=2 emits up
        to 6 tokens per dispatch and stays target-greedy-exact."""
        lm = _lm()
        prompts = _prompts(rng, (3, 9, 17))
        refs = [np.asarray(lm.generate(p[None], 9))[0] for p in prompts]
        srv = DecodeServer(lm, slots=2, max_len=96, draft_layers=2,
                           spec_tokens=2, fuse_steps=2)
        reqs = [srv.submit(p, 9) for p in prompts]
        srv.drain()
        for req, ref in zip(reqs, refs):
            assert np.array_equal(req.output, ref)
        assert srv.stats()["tokens_per_slot_dispatch"] > 1.0

    def test_sampled_spec_matches_target_distribution(self):
        """Accept/resample correctness, statistically: the marginal of
        a decode-phase token under speculative sampling stays within a
        total-variation bound of the vanilla sampled server's (exact
        per-token identity is NOT expected — the RNG consumption
        differs; the DISTRIBUTION must not)."""
        V = 13
        lm = TransformerLM(vocab_size=V, d_model=16, num_heads=2,
                           num_layers=2, max_len=32, seed=5).init()
        prompt = np.array([1, 2, 3], np.int32)
        n = 300

        def freqs(**kw):
            srv = DecodeServer(lm, slots=1, max_len=32, temperature=0.9,
                               **kw)
            c = np.zeros(V)
            for s in range(n):
                req = srv.submit(prompt, 4, seed=s)
                srv.drain()
                c[req.tokens[2]] += 1
            return c / n

        ref = freqs()
        spec = freqs(draft_layers=1, spec_tokens=2)
        tv = 0.5 * np.abs(ref - spec).sum()
        assert tv < 0.15, tv

    def test_env_flag_and_validation(self, rng, monkeypatch):
        monkeypatch.setenv("DL4J_SERVE_DRAFT_LAYERS", "1")
        assert serve_draft_layers() == 1
        lm = _lm()
        srv = DecodeServer(lm, slots=1, max_len=96)
        assert srv.engine.spec
        assert srv.engine.draft_model.num_layers == 1
        monkeypatch.delenv("DL4J_SERVE_DRAFT_LAYERS")
        with pytest.raises(ValueError):
            DecodeServer(lm, slots=1, max_len=96, draft_layers=3)
        with pytest.raises(ValueError):
            DecodeServer(lm, slots=1, max_len=96, draft_layers=1,
                         spec_tokens=0)
        with pytest.raises(ValueError):
            # draft vocab mismatch
            DecodeServer(lm, slots=1, max_len=96,
                         draft_model=_lm(vocab_size=32))

    def test_spec_capacity_needs_verify_slack(self, rng):
        """The verify forward writes spec_tokens candidates past the
        live cursor: submit() reserves that slack against max_len."""
        lm = _lm()
        srv = DecodeServer(lm, slots=1, max_len=32, draft_layers=1,
                           spec_tokens=4)
        with pytest.raises(ValueError):
            srv.submit(_prompts(rng, (20,))[0], 9)   # 29 + 4 > 32
        req = srv.submit(_prompts(rng, (20,))[0], 8)  # 28 + 4 == 32
        srv.drain()
        assert len(req.tokens) == 8
