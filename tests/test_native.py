"""C++ host runtime tests: parser parity vs the Python paths, streamer
read-ahead, and graceful degradation (the native layer is an accelerator,
never a behavior change)."""

import os
import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    SVMLightRecordReader,
)

pytestmark = pytest.mark.skipif(
    not native.is_available(), reason="native toolchain unavailable")


@pytest.fixture
def csv_file(tmp_path):
    p = tmp_path / "data.csv"
    rng = np.random.default_rng(0)
    mat = rng.normal(size=(50, 5)).astype(np.float32)
    with open(p, "w") as f:
        f.write("a,b,c,d,e\n")  # header
        for row in mat:
            f.write(",".join(repr(float(v)) for v in row) + "\n")
    return str(p), mat


class TestCsv:
    def test_parse_matches_numpy(self, csv_file):
        path, mat = csv_file
        out = native.csv_to_array(path, ",", skip_lines=1)
        assert out is not None and out.shape == (50, 5)
        np.testing.assert_allclose(out, mat, rtol=1e-6)

    def test_non_numeric_returns_none(self, tmp_path):
        p = tmp_path / "iris.csv"
        p.write_text("1.0,2.0,setosa\n3.0,4.0,versicolor\n")
        assert native.csv_to_array(str(p)) is None

    def test_ragged_returns_none(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2,3\n4,5\n")
        assert native.csv_to_array(str(p)) is None

    def test_missing_file_returns_none(self, tmp_path):
        assert native.csv_to_array(str(tmp_path / "nope.csv")) is None

    def test_crlf_and_blank_lines(self, tmp_path):
        p = tmp_path / "crlf.csv"
        p.write_bytes(b"1,2\r\n\r\n3,4\r\n")
        out = native.csv_to_array(str(p))
        np.testing.assert_allclose(out, [[1, 2], [3, 4]])

    def test_reader_fast_path_matches_python(self, csv_file):
        path, mat = csv_file
        r = CSVRecordReader(path, skip_lines=1)
        rows = [r.next() for _ in iter(r.has_next, False)]
        assert len(rows) == 50
        np.testing.assert_allclose(
            np.asarray([[float(v) for v in row] for row in rows]),
            mat, rtol=1e-6)


class TestSvmLight:
    def test_parse_matches_python_reader(self, tmp_path):
        p = tmp_path / "data.svm"
        p.write_text("1 1:0.5 3:2.0\n0 2:1.5\n# comment\n2 1:1 2:2 3:3 4:4\n")
        feats, labels = native.svmlight_to_arrays(str(p), 4)
        np.testing.assert_allclose(labels, [1, 0, 2])
        np.testing.assert_allclose(
            feats,
            [[0.5, 0, 2.0, 0], [0, 1.5, 0, 0], [1, 2, 3, 4]])

    def test_reader_uses_native(self, tmp_path):
        p = tmp_path / "d.svm"
        p.write_text("1 1:0.5 2:1.5\n0 2:3.0\n")
        r = SVMLightRecordReader(str(p), num_features=2)
        label, x = r.next()
        assert r._native is not None  # fast path engaged
        assert label == 1.0
        np.testing.assert_allclose(x, [0.5, 1.5])

    def test_out_of_range_index_returns_none(self, tmp_path):
        p = tmp_path / "bad.svm"
        p.write_text("1 7:0.5\n")
        assert native.svmlight_to_arrays(str(p), 4) is None


class TestIdx:
    def test_mnist_style_images(self, tmp_path):
        p = tmp_path / "images.idx3-ubyte"
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, (6, 4, 3), dtype=np.uint8)
        with open(p, "wb") as f:
            f.write(struct.pack(">BBBB", 0, 0, 0x08, 3))
            f.write(struct.pack(">III", 6, 4, 3))
            f.write(imgs.tobytes())
        out = native.idx_to_array(str(p))
        assert out.shape == (6, 4, 3)
        np.testing.assert_allclose(out, imgs.astype(np.float32))

    def test_labels_vector(self, tmp_path):
        p = tmp_path / "labels.idx1-ubyte"
        labels = np.asarray([3, 1, 4, 1, 5], np.uint8)
        with open(p, "wb") as f:
            f.write(struct.pack(">BBBB", 0, 0, 0x08, 1))
            f.write(struct.pack(">I", 5))
            f.write(labels.tobytes())
        out = native.idx_to_array(str(p))
        np.testing.assert_allclose(out, labels)

    def test_truncated_returns_none(self, tmp_path):
        p = tmp_path / "trunc.idx"
        with open(p, "wb") as f:
            f.write(struct.pack(">BBBB", 0, 0, 0x08, 1))
            f.write(struct.pack(">I", 100))  # claims 100, has 0
        assert native.idx_to_array(str(p)) is None


class TestFileStreamer:
    def test_reads_all_chunks_in_order(self, tmp_path):
        p = tmp_path / "blob.bin"
        data = bytes(range(256)) * 40  # 10240 bytes
        p.write_bytes(data)
        got = b""
        with native.FileStreamer(str(p), chunk_bytes=1024, capacity=3) as s:
            for chunk in s:
                got += chunk
        assert got == data

    def test_partial_final_chunk(self, tmp_path):
        p = tmp_path / "odd.bin"
        p.write_bytes(b"x" * 2500)
        sizes = []
        with native.FileStreamer(str(p), chunk_bytes=1000) as s:
            for chunk in s:
                sizes.append(len(chunk))
        assert sizes == [1000, 1000, 500]

    def test_early_close_no_hang(self, tmp_path):
        p = tmp_path / "big.bin"
        p.write_bytes(b"y" * 100_000)
        s = native.FileStreamer(str(p), chunk_bytes=64, capacity=2)
        assert s.next() is not None
        s.close()  # reader thread blocked on full ring must exit


class TestReviewRegressions:
    def test_empty_svmlight_returns_empty_not_crash(self, tmp_path):
        p = tmp_path / "empty.svm"
        p.write_text("# only a comment\n\n")
        out = native.svmlight_to_arrays(str(p), 4)
        assert out is not None
        feats, labels = out
        assert feats.shape == (0, 4) and labels.shape == (0,)

    def test_python_fallback_rejects_out_of_range_index(self, tmp_path):
        p = tmp_path / "bad.svm"
        p.write_text("1 0:5.0\n")  # index 0 in one-based mode
        r = SVMLightRecordReader(str(p), num_features=4)
        r._native = None  # force the Python path
        r._lines = ["1 0:5.0"]
        with pytest.raises(ValueError, match="out of range"):
            r.next()

    def test_csv_numeric_rows_are_floats_both_paths(self, tmp_path):
        p = tmp_path / "num.csv"
        p.write_text("1.5,2.5\n3.5,4.5\n")
        r = CSVRecordReader(str(p))
        row = r.next()
        assert isinstance(row, np.ndarray) and row.dtype == np.float32
        np.testing.assert_allclose(row, [1.5, 2.5])
