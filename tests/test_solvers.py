"""Standalone solver tests on convex/nonconvex toys.

The reference's ``optimize/solver/TestOptimizers.java`` (921 LoC) runs each
OptimizationAlgorithm against Sphere / Rosenbrock / Rastrigin "models" and
asserts score decrease; same here via optimize.minimize over jitted
value-and-grad callables.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.enums import OptimizationAlgorithm
from deeplearning4j_tpu.optimize import (
    EpsTermination, Norm2Termination, ZeroDirection, minimize)

ALGOS = [
    OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT,
    OptimizationAlgorithm.LINE_GRADIENT_DESCENT,
    OptimizationAlgorithm.CONJUGATE_GRADIENT,
    OptimizationAlgorithm.LBFGS,
]


def make_vg(f):
    vg = jax.jit(jax.value_and_grad(f))
    return lambda p: tuple(map(np.asarray, vg(jnp.asarray(p))))


def sphere(x):
    return jnp.sum(x * x)


def rosenbrock(x):
    return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2
                   + (1.0 - x[:-1]) ** 2)


def rastrigin(x):
    return jnp.sum(x * x - 10.0 * jnp.cos(2.0 * jnp.pi * x) + 10.0)


@pytest.mark.parametrize("algo", ALGOS)
def test_sphere_converges_to_zero(algo, rng):
    x0 = rng.normal(0, 2, 10)
    params, score, hist = minimize(
        make_vg(sphere), x0, algo=algo, iterations=200, learning_rate=0.1)
    assert score < 1e-3
    assert hist[-1] <= hist[0]
    # returned score must describe the returned params
    np.testing.assert_allclose(score, float(sphere(jnp.asarray(params))),
                               rtol=1e-5, atol=1e-9)


@pytest.mark.parametrize("algo", [OptimizationAlgorithm.CONJUGATE_GRADIENT,
                                  OptimizationAlgorithm.LBFGS])
def test_rosenbrock_second_order(algo, rng):
    """CG/LBFGS should make strong progress on the banana valley."""
    x0 = np.full(6, -1.2)
    params, score, hist = minimize(
        make_vg(rosenbrock), x0, algo=algo, iterations=500,
        max_line_search_iterations=20)
    # from ~3500 at x0; CG with Armijo (not Wolfe) stalls earlier than LBFGS
    limit = 1.0 if algo == OptimizationAlgorithm.LBFGS else 20.0
    assert score < limit
    assert hist[-1] < hist[0] * 1e-2


@pytest.mark.parametrize("algo", ALGOS)
def test_rastrigin_score_decreases(algo, rng):
    """Nonconvex: only assert monotone-ish improvement (reference does the
    same — score decrease, not global optimum)."""
    x0 = rng.uniform(-0.5, 0.5, 8)  # near basin of global min
    # rastrigin curvature reaches 10·(2π)² ≈ 395: SGD needs lr < 2/395
    params, score, hist = minimize(
        make_vg(rastrigin), x0, algo=algo, iterations=100,
        learning_rate=0.001, max_line_search_iterations=10)
    assert score < hist[0]


def test_lbfgs_beats_sgd_on_rosenbrock():
    x0 = np.full(4, -1.2)
    _, s_lbfgs, _ = minimize(make_vg(rosenbrock), x0,
                             algo=OptimizationAlgorithm.LBFGS,
                             iterations=200, max_line_search_iterations=20)
    _, s_sgd, _ = minimize(
        make_vg(rosenbrock), x0,
        algo=OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT,
        iterations=200, learning_rate=1e-3)
    assert s_lbfgs < s_sgd


class TestTerminations:
    def test_norm2_stops_at_minimum(self):
        x0 = np.ones(4) * 3.0
        _, _, hist = minimize(
            make_vg(sphere), x0, algo=OptimizationAlgorithm.LBFGS,
            iterations=10_000,
            terminations=(Norm2Termination(1e-6),))
        assert len(hist) < 10_000

    def test_eps_stops_on_plateau(self):
        x0 = np.ones(4)
        _, _, hist = minimize(
            make_vg(sphere), x0,
            algo=OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT,
            iterations=10_000, learning_rate=0.2,
            terminations=(EpsTermination(1e-12),))
        assert len(hist) < 10_000

    def test_zero_direction_on_flat(self):
        flat = lambda x: jnp.sum(x * 0.0)
        _, _, hist = minimize(
            make_vg(flat), np.ones(3),
            algo=OptimizationAlgorithm.LINE_GRADIENT_DESCENT,
            iterations=50, terminations=(ZeroDirection(),))
        assert len(hist) == 1

    def test_callback_sees_each_iteration(self):
        seen = []
        minimize(make_vg(sphere), np.ones(3),
                 algo=OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT,
                 iterations=5, learning_rate=0.1, terminations=(),
                 callback=lambda p, s, i: seen.append(i))
        assert seen == [0, 1, 2, 3, 4]

    def test_unknown_algorithm_raises(self):
        with pytest.raises(ValueError):
            minimize(make_vg(sphere), np.ones(2), algo="NOT_AN_ALGO",
                     iterations=1)
