"""Mixed-precision MFU push: the ``mixed_bf16`` master-weights policy.

The training mode the ISSUE-14 tentpole makes first-class: forward/
backward run on a bf16 parameter copy derived ONCE per step, gradients
upcast ONCE, and the updater applies to f32 master weights + f32 updater
state — the state the fused epoch program carries, donates, and
checkpoints. This suite pins the contracts:

- loss-curve parity ≤ 1e-2 vs float32 through the FUSED epoch pipeline
  (FF + graph) and the transformer train step;
- masters stay f32 (params + updater state) across fused training;
- telemetry-on/off stays BITWISE under the mixed policy, the NaN
  sentinel composes (a poisoned batch = exactly one skipped update),
  chunking is bitwise-invariant, accumulation composes;
- flash-vs-XLA attention parity at the fused-multi-step level under the
  mixed policy (interpret mode on CPU) — test_pallas.py covers the
  kernel, this covers the training-step wiring that flips per
  ``attn_impl`` / ``DL4J_ATTN_IMPL``;
- preempt → resume round-trips the masters BITWISE through the
  checkpoint (resume re-derives the bf16 copy in-program);
- the PR-7 contract checker passes over the mixed program (donation
  actually applied to masters + updater state);
- the fused updater apply is ONE flattened sweep: the optimizer tail's
  updater-math op count is depth-invariant (the PR-11 scan-body test's
  shape), and the grouped sweep is bitwise the per-layer reference.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import dtypes as dtypes_mod
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, Updater
from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.models.transformer import TransformerLM
from deeplearning4j_tpu.nn.updater import (
    UpdaterSpec,
    apply_updater,
    grouped_apply_updaters,
    init_updater_state,
)
from deeplearning4j_tpu.parallel.cluster import FaultTolerantTrainer
from deeplearning4j_tpu.resilience import fail_nth, inject


def _ff_net(policy="mixed_bf16", seed=7, updater=Updater.ADAM):
    conf = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(updater).dtype_policy(policy).list()
        .layer(0, L.DenseLayer(n_in=6, n_out=8, activation="tanh"))
        .layer(1, L.OutputLayer(n_in=8, n_out=3))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _graph_net(policy="mixed_bf16", seed=7):
    g = (
        NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.05)
        .updater(Updater.ADAM).dtype_policy(policy)
        .graph_builder()
        .add_inputs("in")
        .add_layer("dense", L.DenseLayer(n_in=6, n_out=8,
                                         activation="tanh"), "in")
        .add_layer("out", L.OutputLayer(n_in=8, n_out=3), "dense")
        .set_outputs("out")
    )
    return ComputationGraph(g.build()).init()


def _ff_data(n=64, seed=0, poison_row=None):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 6)).astype(np.float32)
    if poison_row is not None:
        x[poison_row] = np.nan
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _it(batch=16, **kw):
    return ListDataSetIterator(_ff_data(**kw), batch)


def _lm(policy="mixed_bf16", seed=1, attn="auto", depth=2, d=32, heads=4):
    return TransformerLM(vocab_size=61, d_model=d, num_heads=heads,
                        num_layers=depth, max_len=32, seed=seed,
                        dtype_policy=policy, attn_impl=attn).init()


def _toks(b=2, t=24, seed=0):
    return jnp.asarray(np.random.default_rng(seed).integers(
        0, 61, (b, t)), jnp.int32)


def _assert_bitwise(a, b):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# the policy itself
# ---------------------------------------------------------------------------


class TestPolicy:
    def test_mixed_bf16_resolves_to_master_weights(self):
        p = dtypes_mod.policy_from_name("mixed_bf16")
        assert p.master_weights
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.bfloat16
        # the legacy per-use-cast policy is untouched
        for name in ("bf16", "mixed_bfloat16"):
            assert not dtypes_mod.policy_from_name(name).master_weights

    def test_compute_copy_and_master_grads(self):
        p = dtypes_mod.MIXED_BF16_MASTER
        tree = {"W": jnp.ones((3, 2), jnp.float32)}
        copy = p.compute_copy(tree)
        assert copy["W"].dtype == jnp.bfloat16
        up = p.master_grads({"W": jnp.ones((3, 2), jnp.bfloat16)})
        assert up["W"].dtype == jnp.float32
        # identity under the single-dtype policies
        assert dtypes_mod.FLOAT32.compute_copy(tree) is tree
        assert dtypes_mod.FLOAT32.master_grads(tree) is tree

    def test_grad_zeros_carry_param_dtype(self):
        p = dtypes_mod.MIXED_BF16_MASTER
        z = p.grad_zeros({"W": jnp.ones((2, 2), jnp.bfloat16)})
        assert z["W"].dtype == jnp.float32 and z["W"].shape == (2, 2)


# ---------------------------------------------------------------------------
# fused-epoch training under the mixed policy
# ---------------------------------------------------------------------------


class TestFusedEpochMixed:
    def test_ff_loss_curve_parity_vs_f32(self):
        h32 = _ff_net("float32").fit_epochs(_it(), 3)
        net = _ff_net("mixed_bf16")
        hmx = net.fit_epochs(_it(), 3)
        assert hmx is not None and hmx.shape == (3, 4)
        assert np.abs(np.asarray(h32) - np.asarray(hmx)).max() <= 1e-2
        # masters + updater state stay f32 across fused training
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(net.updater_state):
            assert leaf.dtype == jnp.float32

    def test_graph_loss_curve_parity_vs_f32(self):
        h32 = _graph_net("float32").fit_epochs(_it(), 3)
        net = _graph_net("mixed_bf16")
        hmx = net.fit_epochs(_it(), 3)
        assert hmx is not None
        assert np.abs(np.asarray(h32) - np.asarray(hmx)).max() <= 1e-2
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert leaf.dtype == jnp.float32

    def test_fused_vs_per_step_bitwise(self):
        """The test_epoch_cache bitwise contract holds under the mixed
        policy: fit_epochs vs the per-step train program driven on the
        fused path's exact RNG stream — same bf16 copies, same f32
        master updates, bit for bit."""
        from deeplearning4j_tpu.perf.epoch_cache import (
            DeviceDataSetCache, epoch_schedule)

        fused, ref = _ff_net(), _ff_net()
        cache = DeviceDataSetCache.build(_it())
        hist = fused.fit_epochs(cache, 3)
        keys = jax.random.split(ref._rng, 4)
        ref._rng = keys[0]
        it = 0
        ref_hist = []
        for ekey in keys[1:]:
            order, skeys = epoch_schedule(ekey, cache.n_batches, True)
            row = []
            for j in range(cache.n_batches):
                i = int(np.asarray(order)[j])
                (ref.params, ref.updater_state, ref.net_state, _,
                 loss) = ref._train_step(
                    ref.params, ref.updater_state, ref.net_state,
                    jnp.asarray(it, jnp.int32), jnp.asarray(1.0),
                    cache.features[i], cache.labels[i], None,
                    cache.labels_mask[i], skeys[j], None)
                it += 1
                row.append(np.asarray(loss))
            ref_hist.append(row)
        np.testing.assert_array_equal(np.asarray(hist),
                                      np.asarray(ref_hist))
        _assert_bitwise(fused.params, ref.params)
        _assert_bitwise(fused.updater_state, ref.updater_state)

    def test_telemetry_on_off_bitwise(self):
        a = _ff_net()
        a.fit_epochs(_it(), 3, telemetry=False)
        b = _ff_net()
        b.fit_epochs(_it(), 3, telemetry=True)
        assert b._last_metrics is not None
        assert b._last_metrics.shape == (3, 4, 4)
        # the pack's norms are f32 over the upcast grads
        assert b._last_metrics.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(b._last_metrics)))
        _assert_bitwise(a.params, b.params)

    def test_guard_composes_one_poisoned_batch_one_skip(self):
        net = _ff_net()
        hist = net.fit_epochs(_it(poison_row=20), 2, shuffle=False,
                              guard="skip")
        assert hist is not None
        trips = np.asarray(net._last_sentinel)
        assert trips.shape == (2, 4)
        # the poisoned batch trips once per epoch; every other update
        # applies and the masters stay finite
        assert trips.sum(axis=1).tolist() == [1, 1]
        for leaf in jax.tree_util.tree_leaves(net.params):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_accumulation_composes(self):
        a = _ff_net()
        ha = a.fit_epochs(_it(), 2, shuffle=False, accum_steps=1)
        b = _ff_net()
        hb = b.fit_epochs(_it(), 2, shuffle=False, accum_steps=2)
        # bf16 microbatch grads upcast into an f32 sum: equal to the
        # unaccumulated bf16 step up to bf16 rounding of the per-micro
        # grads, well inside the policy's parity budget
        assert np.abs(np.asarray(ha) - np.asarray(hb)).max() <= 1e-2

    def test_contract_checker_green_over_mixed_program(self):
        from deeplearning4j_tpu.analysis.contracts import (
            check_network_contracts)

        net = _ff_net()
        cache = net.build_epoch_cache(_it())
        net.fit_epochs(cache, 2, telemetry=True)
        # raises ContractViolation on any failure: donation must be
        # applied to every master/updater/net-state leaf of the lowered
        # mixed program, no host callbacks, outputs match the key
        results = check_network_contracts(net, cache)
        assert results and all(not v for v in results.values())


# ---------------------------------------------------------------------------
# transformer: mixed masters + the flash training path
# ---------------------------------------------------------------------------


class TestTransformerMixed:
    def test_master_state_layout_and_parity_vs_f32(self):
        tok = _toks()
        lmf = _lm("float32")
        lmm = _lm("mixed_bf16")
        assert lmm.params["embed"].dtype == jnp.float32
        assert lmm.opt_state["embed"]["m"].dtype == jnp.float32
        diffs = []
        for _ in range(5):
            la = lmf.fit_batch(tok)
            lb = lmm.fit_batch(tok)
            diffs.append(abs(la - lb))
        assert max(diffs) <= 1e-2
        # masters still f32 after donated steps
        assert lmm.params["embed"].dtype == jnp.float32

    def test_fused_multi_step_flash_vs_xla_under_mixed(self):
        """The fused-training-program-level flash/XLA equivalence the
        kernel tests cannot see: K optimizer steps as ONE program per
        attention impl (interpret-mode Pallas on CPU), same losses and
        same trained masters to bf16 tolerance."""
        tok = _toks(t=16)
        lms = {}
        for impl in ("xla", "flash"):
            lm = _lm("mixed_bf16", attn=impl)
            multi = lm.make_multi_train_step(3)
            loss = lm.fit_batch_multi(tok, multi_step=multi, k=3)
            lms[impl] = (lm, loss)
        assert abs(lms["xla"][1] - lms["flash"][1]) <= 2e-2
        for a, b in zip(jax.tree_util.tree_leaves(lms["xla"][0].params),
                        jax.tree_util.tree_leaves(lms["flash"][0].params)):
            assert np.abs(np.asarray(a) - np.asarray(b)).max() <= 1e-2

    def test_attn_env_override(self, monkeypatch):
        lm = _lm()
        monkeypatch.setenv("DL4J_ATTN_IMPL", "flash")
        assert lm._attn_impl(16, train=True) == "flash"
        assert lm._attn_impl(16) == "flash"
        monkeypatch.setenv("DL4J_ATTN_IMPL", "xla")
        assert lm._attn_impl(4096, train=True) == "xla"
        monkeypatch.setenv("DL4J_ATTN_IMPL", "bogus")
        with pytest.raises(ValueError):
            lm._attn_impl(16)

    def test_auto_training_default_flips_flash_when_head_dim_tiles(
            self, monkeypatch):
        import deeplearning4j_tpu.models.transformer as tf_mod

        # pretend a real TPU backend is attached
        monkeypatch.setattr(tf_mod, "flash_default_interpret",
                            lambda: False)
        big = TransformerLM(vocab_size=61, d_model=512, num_heads=8,
                            max_len=1024, num_layers=1)
        assert big._head_dim_tiles()
        # training: flash regardless of sequence length
        assert big._attn_impl(1024, train=True) == "flash"
        # inference keeps the measured t>=4k crossover
        assert big._attn_impl(1024) == "xla"
        assert big._attn_impl(4096) == "flash"
        small = TransformerLM(vocab_size=61, d_model=32, num_heads=4,
                              max_len=1024, num_layers=1)
        assert not small._head_dim_tiles()
        assert small._attn_impl(1024, train=True) == "xla"

    def test_interpret_backend_stays_on_xla(self):
        # CPU (interpret-mode Pallas) never auto-selects flash
        lm = _lm()
        assert lm._attn_impl(1024, train=True) == "xla"


# ---------------------------------------------------------------------------
# the fused (grouped) updater apply
# ---------------------------------------------------------------------------


def _adam_mln(depth, seed=3):
    b = (NeuralNetConfiguration.Builder().seed(seed).learning_rate(0.01)
         .updater(Updater.ADAM).list())
    for i in range(depth):
        b = b.layer(i, L.DenseLayer(n_in=8, n_out=8, activation="tanh"))
    b = b.layer(depth, L.OutputLayer(n_in=8, n_out=4))
    return MultiLayerNetwork(b.build()).init()


UPDATER_MATH_PRIMS = {"sqrt", "rsqrt", "integer_pow", "pow", "div"}


def _updater_tail_math_eqns(net):
    grads = jax.tree_util.tree_map(jnp.ones_like, net.params)
    jaxpr = jax.make_jaxpr(
        lambda p, u, g: net._apply_updaters(
            p, u, g, jnp.asarray(0, jnp.int32), jnp.asarray(1.0)))(
        net.params, net.updater_state, grads)
    names = []
    stack = [jaxpr.jaxpr]
    while stack:
        j = stack.pop()
        for e in j.eqns:
            names.append(e.primitive.name)
            for v in e.params.values():
                if hasattr(v, "jaxpr"):
                    stack.append(v.jaxpr)
    return sum(1 for n in names if n in UPDATER_MATH_PRIMS)


class TestFusedUpdaterSweep:
    def test_optimizer_tail_math_is_depth_invariant(self):
        """The PR-11 scan-body assertion shape, on the optimizer tail:
        the traced Adam math (sqrt/pow/div chains) is per GROUP, not
        per layer — its op count must not move with depth. (The per-leaf
        residue is only reshape/slice data movement.)"""
        shallow = _updater_tail_math_eqns(_adam_mln(2))
        deep = _updater_tail_math_eqns(_adam_mln(8))
        assert shallow == deep, (shallow, deep)

    @pytest.mark.parametrize("kind", [Updater.SGD, Updater.NESTEROVS,
                                      Updater.ADAGRAD, Updater.RMSPROP,
                                      Updater.ADADELTA, Updater.ADAM])
    def test_grouped_matches_per_layer_reference(self, kind):
        """Bitwise against the pre-PR-14 per-layer loop: elementwise
        updater ops on a concatenation ARE the per-leaf ops."""
        rng = np.random.default_rng(abs(hash(str(kind))) % 1000)
        specs = [UpdaterSpec(kind=kind, learning_rate=0.05),
                 UpdaterSpec(kind=kind, learning_rate=0.05)]
        params, state, grads = {}, {}, {}
        for i in range(2):
            p = {"W": jnp.asarray(rng.normal(size=(4, 3)), jnp.float32),
                 "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}
            params[str(i)] = p
            state[str(i)] = init_updater_state(specs[i], p)
            grads[str(i)] = jax.tree_util.tree_map(
                lambda a: jnp.asarray(
                    rng.normal(size=a.shape), jnp.float32), p)
        scale = jnp.asarray(1.0)
        step_count = jnp.asarray(2)
        new_p, new_u = grouped_apply_updaters(
            [(str(i), specs[i]) for i in range(2)], params, state,
            grads, scale, step_count)
        # reference: the per-layer loop this PR replaced
        ref_p, ref_u = {}, {}
        for i, spec in enumerate(specs):
            si = str(i)
            steps_i, upd_i = apply_updater(
                spec, grads[si], state[si], scale, step_count)
            ref_p[si] = jax.tree_util.tree_map(
                lambda p, s: p - s.astype(p.dtype), params[si], steps_i)
            ref_u[si] = upd_i
        _assert_bitwise(new_p, ref_p)
        _assert_bitwise(new_u, ref_u)
        assert (jax.tree_util.tree_structure(new_p)
                == jax.tree_util.tree_structure(ref_p))

    def test_tp_sharded_state_takes_the_per_layer_fallback(self):
        """GSPMD miscompiles the ravel→concat→slice chain over leaves
        with MIXED shardings (verified on jax 0.4.37) — the flat sweep
        must refuse tensor-parallel placements and fall back to the
        per-layer apply. End-to-end: a TP-sharded per-step fit matches
        the unsharded reference (the pre-PR-14 test_parallel contract)."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.nn.updater import flat_apply_safe
        from deeplearning4j_tpu.parallel import MeshSpec, build_mesh
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            shard_network_params)

        ref, tp = _ff_net("float32"), _ff_net("float32")
        assert flat_apply_safe(ref.params)
        mesh = build_mesh(MeshSpec(data=2, model=4))
        shard_network_params(tp, mesh)
        assert not flat_apply_safe(tp.params)
        rng = np.random.default_rng(3)
        ds = DataSet(rng.normal(size=(16, 6)).astype(np.float32),
                     np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])
        ref.fit(ds)
        with mesh:
            tp.fit(ds)
        np.testing.assert_allclose(ref.get_flat_params(),
                                   tp.get_flat_params(),
                                   rtol=2e-4, atol=1e-5)

    def test_bias_lr_and_per_layer_normalization_preserved(self):
        from deeplearning4j_tpu.nn.conf.enums import GradientNormalization

        rng = np.random.default_rng(5)
        specs = [
            UpdaterSpec(kind=Updater.SGD, learning_rate=0.1,
                        bias_learning_rate=0.01),
            UpdaterSpec(
                kind=Updater.SGD, learning_rate=0.1,
                gradient_normalization=(
                    GradientNormalization.CLIP_L2_PER_LAYER),
                gradient_normalization_threshold=0.5),
        ]
        params, state, grads = {}, {}, {}
        for i, spec in enumerate(specs):
            p = {"W": jnp.asarray(rng.normal(size=(4, 3)), jnp.float32),
                 "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}
            params[str(i)] = p
            state[str(i)] = init_updater_state(spec, p)
            grads[str(i)] = jax.tree_util.tree_map(
                lambda a: jnp.asarray(
                    rng.normal(size=a.shape) * 3.0, jnp.float32), p)
        new_p, _ = grouped_apply_updaters(
            [(str(i), specs[i]) for i in range(2)], params, state,
            grads, jnp.asarray(1.0), jnp.asarray(1))
        # layer 0: bias stepped with its own lr
        np.testing.assert_allclose(
            np.asarray(new_p["0"]["b"]),
            np.asarray(params["0"]["b"] - 0.01 * grads["0"]["b"]),
            rtol=0, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(new_p["0"]["W"]),
            np.asarray(params["0"]["W"] - 0.1 * grads["0"]["W"]),
            rtol=0, atol=1e-7)
        # layer 1: clipped with the LAYER's own norm (not the group's)
        from deeplearning4j_tpu.nn.updater import normalize_gradients

        g1 = normalize_gradients(specs[1], grads["1"])
        np.testing.assert_allclose(
            np.asarray(new_p["1"]["W"]),
            np.asarray(params["1"]["W"] - 0.1 * g1["W"]),
            rtol=0, atol=1e-7)


# ---------------------------------------------------------------------------
# preempt -> resume: masters round-trip through the checkpoint
# ---------------------------------------------------------------------------


@pytest.mark.chaos
class TestPreemptResumeMixed:
    def test_masters_round_trip_bitwise(self, tmp_path):
        """Preempt a mixed_bf16 fused run at a chunk boundary, resume in
        a fresh process-equivalent, finish: bitwise the uninterrupted
        run. The checkpoint stores the f32 MASTERS (params/updater state
        are never bf16 at rest); resume re-derives the bf16 copy
        in-program on the first step."""
        base = _ff_net()
        base.fit_epochs(_it(), 4, chunk_epochs=1)

        n2 = _ff_net()
        t2 = FaultTolerantTrainer(n2, str(tmp_path))
        with inject("preempt.chunk", fail_nth(2)):
            t2.fit_epochs(_it(), 4, chunk_epochs=1)
        assert t2.preempted and n2._epoch_cursor == 2

        n3 = _ff_net()
        t3 = FaultTolerantTrainer(n3, str(tmp_path))
        assert t3.resume()
        # the restored state is the f32 masters
        for leaf in jax.tree_util.tree_leaves(n3.params):
            assert leaf.dtype == jnp.float32
        t3.fit_epochs(_it(), 4, chunk_epochs=1)
        _assert_bitwise(base.params, n3.params)
        _assert_bitwise(base.updater_state, n3.updater_state)
        assert base.iteration_count == n3.iteration_count
