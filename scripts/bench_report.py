#!/usr/bin/env python
"""Bench-trajectory report: round-over-round table + regression gate.

The BENCH_r01..r05 trajectory degraded silently: rounds 4-5 recorded
wedged-grant error lines and nothing machine-readable ever diffed one
round against the last honest one. This reads every ``BENCH_r*.json``
(the driver sidecar shape ``{n, rc, tail, parsed}``; bare result lines
``{metric, value, extras}`` are accepted too, so synthetic fixtures and
fresh ``bench.py`` output both feed it), classifies each round —

- ``ok``     a result line with a non-null headline value and no error
- ``wedge``  an explicit backend-unavailable / wedged-grant error line
- ``error``  no parseable result line, a nonzero rc, or any other error

— prints the trajectory table (headline value, per-section samples/sec,
MFU, guard/telemetry overhead), and with ``--check`` exits nonzero when
the LATEST ok round regresses more than ``--threshold-pct`` against the
best earlier ok round on any tracked series. Each series carries a
DIRECTION: "higher" (throughput-like — a drop regresses) or "lower"
(latency-like, e.g. the serve section's p50/p99 — a rise regresses).
Wedge and error rounds are called out but never scored (a wedge is an
infrastructure fact, not a perf regression) and never used as a
baseline.

Usage:
    python scripts/bench_report.py BENCH_r*.json           # table only
    python scripts/bench_report.py --check BENCH_r*.json   # gate (rc 1
                                                           # on regression)
    python scripts/bench_report.py --check --threshold-pct 10 ...

Exit codes: 0 clean, 1 regression found (``--check``), 2 usage/load
error. Wired into ``scripts/verify.sh --profile``.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

WEDGE_MARKERS = ("backend unavailable", "wedge", "did not complete")

# (label, extractor, direction) — direction is "higher" (throughput-like:
# a DROP regresses) or "lower" (latency-like: a RISE regresses); the
# extractor returns None when the round has no honest value for it
TRACKED = [
    ("headline", lambda r: r["value"] if r["status"] == "ok" else None,
     "higher"),
    # the scored MFU series is COST-ANALYSIS-ONLY: rounds whose MFU was
    # derived from the analytic formula (pre-PR-8 artifacts, or a round
    # where cost analysis was unavailable) return None and never enter
    # the trajectory — an analytic number comparing against a compiled
    # one is not the same experiment (the table flags such rounds)
    ("transformer_mfu_pct",
     lambda r: (_dig(r, "transformer_lm", "mfu_pct")
                if transformer_flops_source(r) == "cost_analysis"
                else None), "higher"),
    ("transformer_tokens_per_sec",
     lambda r: _dig(r, "transformer_lm", "tokens_per_sec"), "higher"),
    # mixed-precision step speedup (bf16 step vs the f32-policy step at
    # the same config) — the PR-14 MFU push's direct evidence
    ("train_step_bf16_speedup",
     lambda r: _dig(r, "transformer_lm", "train_step_bf16_speedup"),
     "higher"),
    ("resnet18_mfu_pct",
     lambda r: _dig(r, "resnet18_cifar10", "mfu_pct"), "higher"),
    ("resnet18_samples_per_sec",
     lambda r: _dig(r, "resnet18_cifar10", "samples_per_sec"), "higher"),
    ("mnist_mlp_samples_per_sec",
     lambda r: _dig(r, "mnist_mlp", "samples_per_sec"), "higher"),
    ("lenet5_samples_per_sec",
     lambda r: _dig(r, "lenet5", "samples_per_sec"), "higher"),
    ("gemm_peak_tflops",
     lambda r: _dig(r, "gemm", "peak_achieved_tflops"), "higher"),
    ("epoch_speedup",
     lambda r: _dig(r, "epoch", "speedup"), "higher"),
    ("dp_epoch_samples_per_sec_per_chip",
     lambda r: _dig(r, "dp_epoch", "samples_per_sec_per_chip"), "higher"),
    # the serve section: latency percentiles gate lower-is-better —
    # before per-metric direction existed these could only ride in the
    # table, never fail the gate
    ("serve_tokens_per_sec",
     lambda r: _dig(r, "serve", "tokens_per_sec"), "higher"),
    ("serve_p50_latency_ms",
     lambda r: _dig(r, "serve", "p50_latency_ms"), "lower"),
    ("serve_p99_latency_ms",
     lambda r: _dig(r, "serve", "p99_latency_ms"), "lower"),
    ("serve_ttft_p50_ms",
     lambda r: _dig(r, "serve", "ttft_p50_ms"), "lower"),
    # the PR-11 fast path: fused dispatch amortization (fewer host
    # dispatches per token and a lower fused TPOT gate LOWER),
    # speculative acceptance and quantized-pool concurrency gate HIGHER
    ("serve_dispatches_per_token",
     lambda r: _dig(r, "serve", "dispatches_per_token"), "lower"),
    ("serve_tpot_fused_ms",
     lambda r: _dig(r, "serve", "tpot_fused_ms"), "lower"),
    ("serve_accepted_tokens_per_dispatch",
     lambda r: _dig(r, "serve", "accepted_tokens_per_dispatch"),
     "higher"),
    ("serve_max_slots_int8",
     lambda r: _dig(r, "serve", "max_slots_int8"), "higher"),
    # the serve fleet (PR 13): aggregate throughput and 1->2-replica
    # scaling gate higher; fleet latency percentiles and the
    # failover-recovery time gate lower
    ("serve_fleet_tokens_per_sec",
     lambda r: _dig(r, "serve_fleet", "fleet_tokens_per_sec"), "higher"),
    ("serve_fleet_scaling_2r",
     lambda r: _dig(r, "serve_fleet", "tokens_per_sec_scaling_2r"),
     "higher"),
    ("serve_fleet_p99_latency_ms",
     lambda r: _dig(r, "serve_fleet", "p99_latency_ms_2r"), "lower"),
    ("serve_fleet_ttft_p50_ms",
     lambda r: _dig(r, "serve_fleet", "ttft_p50_ms_2r"), "lower"),
    ("serve_fleet_failover_s",
     lambda r: _dig(r, "serve_fleet", "failover_complete_s"), "lower"),
    # the sharding-registry mesh sweep (PR 17): the most-TP shape's
    # fused step time and per-chip HBM — TP must keep shrinking
    # per-chip residency without breaking whole-epoch fusion
    ("mesh_tp_step_ms",
     lambda r: _dig(r, "mesh_sweep", "tp_step_ms"), "lower"),
    ("mesh_tp_per_chip_hbm_mb",
     lambda r: _dig(r, "mesh_sweep", "tp_per_chip_hbm_mb"), "lower"),
    # the fused embeddings push (PR 18): words/sec gates higher (the
    # section's headline words_per_sec switched from the host loop to
    # the fused program this round), dispatches/epoch must stay at 1
    ("w2v_words_per_sec",
     lambda r: _dig(r, "word2vec", "words_per_sec"), "higher"),
    ("w2v_dispatches_per_epoch",
     lambda r: _dig(r, "word2vec", "dispatches_per_epoch"), "lower"),
]

# direction lookup for scored series; headline:* keys inherit "higher"
DIRECTIONS = {label: direction for label, _, direction in TRACKED}


def series_direction(label: str) -> str:
    if label.startswith("headline:"):
        return "higher"
    return DIRECTIONS.get(label, "higher")

# lower-is-better overhead columns: reported in the table, not gated
OVERHEADS = [
    ("guard_overhead_pct", ("guard", "sentinel_overhead_pct")),
    ("telemetry_overhead_pct", ("telemetry", "pack_overhead_pct")),
    ("flight_overhead_pct", ("flight", "flight_overhead_pct")),
]


def _dig(row: dict, section: str, field: str):
    sec = (row.get("extras") or {}).get(section)
    if not isinstance(sec, dict) or "error" in sec:
        return None
    val = sec.get(field)
    return float(val) if isinstance(val, (int, float)) else None


def transformer_flops_source(row: dict):
    """Where the round's transformer MFU FLOPs came from:
    ``"cost_analysis"`` (the PR-8 dual block with a non-null compiled
    count), ``"analytic"`` (a legacy string block, or a dual block whose
    cost-analysis capture failed), or None (no transformer data)."""
    sec = (row.get("extras") or {}).get("transformer_lm")
    if not isinstance(sec, dict) or "error" in sec:
        return None
    src = sec.get("flops_source")
    if isinstance(src, dict):
        return ("cost_analysis"
                if src.get("cost_analysis_flops") is not None
                else "analytic")
    return "analytic" if src is not None else None


def _dig_ledger(row: dict, field: str = "goodput_pct"):
    """Run-ledger fields from the artifact's telemetry block (PR 9):
    ``extras.telemetry.ledger.{goodput_pct, badput, ...}``. Absent on
    pre-ledger rounds — the column just shows '-'."""
    tel = (row.get("extras") or {}).get("telemetry")
    if not isinstance(tel, dict):
        return None
    ledger = tel.get("ledger")
    if not isinstance(ledger, dict):
        return None
    val = ledger.get(field)
    if field == "badput" and isinstance(val, dict):
        return val
    return float(val) if isinstance(val, (int, float)) else None


def _badput_note(row: dict):
    """Compact 'state=seconds' summary of the ledger's badput."""
    bad = _dig_ledger(row, "badput")
    if not bad:
        return None
    return ",".join(f"{k}={v:.1f}s"
                    for k, v in sorted(bad.items(), key=lambda kv: -kv[1]))


def _round_number(path: str, payload: dict) -> Optional[int]:
    n = payload.get("n")
    if isinstance(n, int):
        return n
    m = re.search(r"r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def load_round(path: str) -> dict:
    """One BENCH file -> a normalized row. Accepts the driver sidecar
    shape ({n, rc, tail, parsed}) and a bare result line."""
    with open(path) as f:
        payload = json.load(f)
    if "parsed" in payload or "rc" in payload:
        parsed = payload.get("parsed")
        rc = payload.get("rc", 0)
    else:  # a bare bench.py result line
        parsed = payload
        rc = 0
    row = {
        "path": path,
        "round": _round_number(path, payload),
        "rc": rc,
        "metric": None,
        "value": None,
        "unit": None,
        "extras": {},
        "note": "",
    }
    if isinstance(parsed, dict):
        row["metric"] = parsed.get("metric")
        row["value"] = parsed.get("value")
        row["unit"] = parsed.get("unit")
        row["extras"] = parsed.get("extras") or {}
    err = (row["extras"].get("error") or "") if row["extras"] else ""
    if parsed is None:
        row["status"] = "error"
        row["note"] = f"no result line (rc={rc})"
    elif err and any(m in err.lower() for m in WEDGE_MARKERS):
        row["status"] = "wedge"
        row["note"] = err[:90]
    elif err or row["value"] is None or rc != 0:
        row["status"] = "error"
        row["note"] = (err or f"null value (rc={rc})")[:90]
    else:
        row["status"] = "ok"
    return row


def build_series(rows: List[dict]) -> Dict[str, List[Tuple[int, float]]]:
    """{series label: [(round, value), ...]} over ok rounds only, and
    only where the round's headline METRIC matches for the headline
    series (r01's lenet headline and r03's transformer headline are
    different experiments, not a trajectory)."""
    series: Dict[str, List[Tuple[int, float]]] = {}
    for label, extract, _direction in TRACKED:
        pts = []
        for row in rows:
            # unnumbered rounds cannot be ordered into a trajectory
            if row["status"] != "ok" or row["round"] is None:
                continue
            val = extract(row)
            if val is not None:
                key = label
                if label == "headline":
                    key = f"headline:{row['metric']}"
                pts.append((key, row["round"], val))
        for key, rnd, val in pts:
            series.setdefault(key, []).append((rnd, val))
    return series


def find_regressions(series: Dict[str, List[Tuple[int, float]]],
                     threshold_pct: float) -> List[str]:
    """Latest ok point vs the best EARLIER ok point per series, where
    "best" follows the series direction: max for higher-is-better
    (throughput — a drop regresses), min for lower-is-better (latency —
    a rise regresses)."""
    out = []
    for label, pts in sorted(series.items()):
        pts = sorted(pts)
        if len(pts) < 2:
            continue
        (last_round, last), earlier = pts[-1], pts[:-1]
        if series_direction(label) == "lower":
            best_round, best = min(earlier, key=lambda p: p[1])
            if best <= 0:
                continue
            delta_pct = 100.0 * (last - best) / best
            verb = "above"
        else:
            best_round, best = max(earlier, key=lambda p: p[1])
            if best <= 0:
                continue
            delta_pct = 100.0 * (best - last) / best
            verb = "below"
        if delta_pct > threshold_pct:
            out.append(
                f"{label}: r{last_round:02d} = {last:,.1f} is "
                f"{delta_pct:.1f}% {verb} r{best_round:02d} = {best:,.1f} "
                f"(threshold {threshold_pct:.0f}%)")
    return out


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float) and abs(v) >= 1000:
        return f"{v:,.0f}"
    return f"{v:g}"


def print_table(rows: List[dict], out=None) -> None:
    out = out or sys.stdout
    cols = ["round", "status", "headline", "value", "tf_mfu%",
            "rn_mfu%", "guard_ov%", "telem_ov%", "goodput%", "badput",
            "note"]
    table = []
    for row in rows:
        note = row["note"]
        if (row["status"] == "ok"
                and transformer_flops_source(row) == "analytic"):
            # the MFU printed beside it came from the hand formula, not
            # the compiled program — excluded from the scored series
            flag = "[flops_source!=cost_analysis]"
            note = f"{note} {flag}".strip() if note else flag
        table.append([
            f"r{row['round']:02d}" if row["round"] is not None else "?",
            row["status"].upper() if row["status"] != "ok" else "ok",
            (row["metric"] or "-")[:44],
            _fmt(row["value"]),
            _fmt(_dig(row, "transformer_lm", "mfu_pct")),
            _fmt(_dig(row, "resnet18_cifar10", "mfu_pct")),
            _fmt(_dig(row, *OVERHEADS[0][1])),
            _fmt(_dig(row, *OVERHEADS[1][1])),
            _fmt(_dig_ledger(row)),
            _badput_note(row) or "-",
            note,
        ])
    widths = [max(len(str(r[i])) for r in [cols] + table)
              for i in range(len(cols))]
    for r in [cols] + table:
        print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)),
              file=out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="bench trajectory table + regression gate")
    ap.add_argument("files", nargs="+", help="BENCH_r*.json artifacts")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when a tracked series regresses")
    ap.add_argument("--threshold-pct", type=float, default=20.0,
                    help="regression threshold (default 20%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (compact per-round "
                         "rows + series + regressions) instead of the "
                         "table")
    args = ap.parse_args(argv)

    rows = []
    for path in sorted(args.files):
        try:
            rows.append(load_round(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_report: cannot load {path}: {e}",
                  file=sys.stderr)
            return 2
    rows.sort(key=lambda r: (r["round"] is None, r["round"]))

    series = build_series(rows)
    regressions = find_regressions(series, args.threshold_pct)

    if args.json:
        # compact rows (extras are megabytes in real artifacts — keep
        # the machine-readable shape to the scored/reported fields)
        compact = []
        for row in rows:
            entry = {
                "round": row["round"], "status": row["status"],
                "metric": row["metric"], "value": row["value"],
                "unit": row["unit"], "rc": row["rc"],
                "note": row["note"],
                "goodput_pct": _dig_ledger(row),
                "badput": _dig_ledger(row, "badput"),
                "transformer_flops_source": transformer_flops_source(row),
            }
            for label, extract, _direction in TRACKED[1:]:
                entry[label] = extract(row)
            for label, keys in OVERHEADS:
                entry[label] = _dig(row, *keys)
            compact.append(entry)
        print(json.dumps({
            "rounds": compact,
            "series": {k: v for k, v in sorted(series.items())},
            "directions": {k: series_direction(k) for k in series},
            "threshold_pct": args.threshold_pct,
            "regressions": regressions,
        }))
        return 1 if (regressions and args.check) else 0

    print_table(rows)
    bad = [r for r in rows if r["status"] != "ok"]
    if bad:
        print()
        for row in bad:
            rid = (f"r{row['round']:02d}" if row["round"] is not None
                   else "r??")
            print(f"  !! {rid} is a "
                  f"{row['status'].upper()} round — excluded from "
                  f"regression scoring: {row['note']}")

    if regressions:
        print("\nREGRESSIONS:")
        for r in regressions:
            print(f"  {r}")
        if args.check:
            return 1
    elif args.check:
        print("\nno regressions beyond "
              f"{args.threshold_pct:.0f}% across "
              f"{sum(1 for r in rows if r['status'] == 'ok')} ok "
              f"round(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
