#!/usr/bin/env bash
# Tier-1 verification gate — the exact command from ROADMAP.md.
# Usage: scripts/verify.sh            (full tier-1: everything not 'slow')
#        scripts/verify.sh -m chaos   (extra pytest args narrow the run,
#                                      e.g. just the fault-injection suite)
#        scripts/verify.sh --eval     (just the eval/inference equivalence
#                                      suite: device-vs-host metrics,
#                                      recompile guard, bucketing)
#        scripts/verify.sh --epoch    (just the epoch-pipeline equivalence
#                                      suite: fit_epochs vs per-step
#                                      bitwise, recompile guard, HBM-budget
#                                      fallback)
#        scripts/verify.sh --dp       (just the data-parallel + sharded
#                                      epoch suites on the forced 8-device
#                                      host mesh: SPMD fit_epochs vs
#                                      single-device, parameter averaging
#                                      vs all-reduce, accumulation)
#        scripts/verify.sh --heal     (just the self-healing suite +
#                                      existing chaos cases: NaN-guard
#                                      policies, preemption + bitwise
#                                      elastic resume, save_async,
#                                      checkpoint corruption/eviction)
#        scripts/verify.sh --obs      (just the observability suites —
#                                      metrics pack parity/values,
#                                      registry, tracer, exporters, run
#                                      ledger, flight recorder, fleet
#                                      heartbeats — plus the
#                                      no-bare-counters lint rule and the
#                                      flight-recorder write → kill -9 →
#                                      report round trip)
#        scripts/verify.sh --serve    (just the online-serving suite —
#                                      batched slot decode vs generate
#                                      equivalence, continuous batching,
#                                      compile flatness, prompt ladder,
#                                      loadgen — plus the host-sync lint
#                                      over the serve hot path)
#        scripts/verify.sh --fleet    (just the serve-fleet suite —
#                                      routing policy, failover token
#                                      identity, controller eviction +
#                                      straggler flagging, prefill/
#                                      decode handoff, virtual-clock
#                                      driver, replica-kill chaos — plus
#                                      the host-sync lint over
#                                      serving/fleet/'s traced slot
#                                      movers)
#        scripts/verify.sh --serve-slo (serve overload-control gate —
#                                      deadline sheds at admission/queue/
#                                      in-flight, criticality displacement,
#                                      retry-budget arithmetic + parked
#                                      failovers, hedging races, graceful
#                                      drain token identity, and the
#                                      3x-capacity storm soak's SLO
#                                      asserts — plus the host-sync and
#                                      lock-discipline lint over serving/)
#        scripts/verify.sh --lint     (static analysis gate: the full
#                                      dl4j-lint ruleset over the tree +
#                                      the program-contract checks and
#                                      rule-engine fixtures in
#                                      tests/test_analysis.py; nonzero
#                                      exit on any NEW finding)
#        scripts/verify.sh --profile  (performance observatory: the
#                                      ProgramProfile/HBM-watermark
#                                      suite + bench_report.py --check
#                                      over the committed BENCH_r*.json
#                                      trajectory; nonzero exit on a
#                                      bench regression)
#        scripts/verify.sh --autopilot (always-on fleet: the grant-lease
#                                      protocol, elastic mid-run reshard
#                                      equivalence, goodput-autopilot
#                                      decision suite, and the bounded
#                                      chaos soak (preempt + wedge +
#                                      straggle + evict, 1e-6 final-state
#                                      + goodput-floor asserts) — plus
#                                      the host-sync and lock-discipline
#                                      lint over the resilience modules)
#        scripts/verify.sh --mfu      (mixed-precision MFU push: the
#                                      mixed_bf16 master-weights suite —
#                                      fused-epoch loss parity vs f32,
#                                      flash-vs-xla training parity,
#                                      preempt→resume master round-trip,
#                                      fused updater-sweep depth
#                                      invariance, contracts over the
#                                      mixed program — plus the
#                                      implicit-f32-promotion lint)
#        scripts/verify.sh --nlp      (the fused-embeddings gate: the
#                                      NLP suites + the fused skip-gram
#                                      equivalence/contract tests and the
#                                      sharded DP/row-sharded parity
#                                      suite, plus the host-sync +
#                                      adhoc-out-shardings lint over
#                                      nlp/ (the chunk driver's ledger/
#                                      heartbeat readbacks must never
#                                      ride into the traced programs;
#                                      table placement routes through
#                                      the registry))
#        scripts/verify.sh --mesh     (the sharding-registry gate: the
#                                      DP×TP registry suite — spec
#                                      totality, fused-epoch parity,
#                                      topology reshard, TP serving —
#                                      plus the TP/PP parallel suites,
#                                      the adhoc-out-shardings lint
#                                      (every placement decision routes
#                                      through the registry) and the
#                                      bench trajectory check)
# The eval/epoch/dp/heal/obs/serve/fleet/serve-slo/lint/profile/mfu/
# mesh tests are part of the default tier-1 run; --eval/--epoch/--dp/
# --heal/--obs/--serve/--fleet/--serve-slo/--lint/--profile/--mfu/
# --mesh are the narrow fast paths for iterating on those surfaces.
set -o pipefail

cd "$(dirname "$0")/.."

TARGET=tests/
if [ "${1:-}" = "--eval" ]; then
    shift
    TARGET=tests/test_eval_device.py
elif [ "${1:-}" = "--epoch" ]; then
    shift
    TARGET=tests/test_epoch_cache.py
elif [ "${1:-}" = "--dp" ]; then
    shift
    TARGET="tests/test_dp_epoch.py tests/test_parallel.py"
elif [ "${1:-}" = "--heal" ]; then
    shift
    TARGET="tests/test_self_healing.py tests/test_resilience.py tests/test_cluster.py"
elif [ "${1:-}" = "--obs" ]; then
    shift
    TARGET="tests/test_telemetry.py tests/test_flight.py"
    # the counters lint rides along with the telemetry suite: no module
    # besides monitor/ may define new bare _*_counter attributes
    # (the old scripts/lint_telemetry.py, absorbed into dl4j-lint)
    python scripts/dl4j_lint.py --select bare-counter || exit 1
    # crash-forensics gate: a flight-recorder child is written to, kill
    # -9'd mid-chunk, and the surviving segments must reconstruct the
    # timeline and classify the death as 'crashed'
    python scripts/flight_report.py --selftest || exit 1
elif [ "${1:-}" = "--serve" ]; then
    shift
    TARGET=tests/test_serving.py
    # the decode loop's host-sync guard rides along: the serve program
    # bodies (serving/engine.py hot roots) must stay free of host
    # readbacks — the one sanctioned [S] token readback lives in
    # server.py, outside the traced surface
    python scripts/dl4j_lint.py --select host-sync-in-hot-path \
        deeplearning4j_tpu/serving || exit 1
elif [ "${1:-}" = "--fleet" ]; then
    shift
    TARGET=tests/test_serving_fleet.py
    # the fleet's traced slot movers (handoff export/import) are hot
    # roots like the engine's program bodies: the per-request handoff
    # readback lives OUTSIDE them (export_slot), and the lint keeps any
    # new sync from riding into the compiled pool programs
    python scripts/dl4j_lint.py --select host-sync-in-hot-path \
        deeplearning4j_tpu/serving || exit 1
elif [ "${1:-}" = "--serve-slo" ]; then
    shift
    TARGET=tests/test_serve_overload.py
    # overload control is control-plane code threaded around the traced
    # decode programs: the shed/hedge/drain paths must add no host syncs
    # to the hot roots and no unlocked cross-thread queue state
    python scripts/dl4j_lint.py \
        --select host-sync-in-hot-path,lock-discipline \
        deeplearning4j_tpu/serving || exit 1
elif [ "${1:-}" = "--lint" ]; then
    shift
    # static-analysis gate: source-level ruleset first (stdlib-only,
    # fails fast), then the jaxpr/HLO program-contract checks + the
    # seeded-violation fixtures that keep the rules themselves honest
    python scripts/dl4j_lint.py || exit 1
    TARGET=tests/test_analysis.py
elif [ "${1:-}" = "--profile" ]; then
    shift
    TARGET=tests/test_profile.py
    # the trajectory gate rides along: the committed BENCH artifacts
    # must show no silent round-over-round regression (wedge/error
    # rounds are called out but never scored)
    python scripts/bench_report.py --check BENCH_r*.json || exit 1
elif [ "${1:-}" = "--autopilot" ]; then
    shift
    TARGET=tests/test_autopilot.py
    # the always-on layer's control plane is host-side by construction:
    # the lease/autopilot/reshard code must introduce no host syncs into
    # traced programs and no unlocked cross-thread state (the lease's
    # daemon-thread attempt + the autopilot's tick both ride threads)
    python scripts/dl4j_lint.py \
        --select host-sync-in-hot-path,lock-discipline \
        deeplearning4j_tpu/resilience deeplearning4j_tpu/perf || exit 1
elif [ "${1:-}" = "--mfu" ]; then
    shift
    TARGET=tests/test_mixed_precision.py
    # the promotion lint rides along: no matmul operand in a traced hot
    # path may reach a param leaf without policy.cast_compute (the bug
    # class that silently runs the bf16 step at f32 MXU rate)
    python scripts/dl4j_lint.py --select implicit-f32-promotion || exit 1
elif [ "${1:-}" = "--nlp" ]; then
    shift
    TARGET="tests/test_nlp.py tests/test_nlp_fused.py tests/test_distributed_nlp.py"
    # the fused embedding programs are hot roots like the dense chunk
    # programs: no host syncs reachable from the traced pair-gen/updater
    # kernels, and no ad-hoc NamedSharding — syn0/syn1neg placement goes
    # through ShardingRegistry.for_embedding_tables
    python scripts/dl4j_lint.py \
        --select host-sync-in-hot-path,adhoc-out-shardings \
        deeplearning4j_tpu/nlp || exit 1
elif [ "${1:-}" = "--mesh" ]; then
    shift
    TARGET="tests/test_sharding_registry.py tests/test_parallel.py tests/test_dp_epoch.py"
    # the one-mesh discipline rides along: NamedSharding construction /
    # out_shardings= pins belong in parallel/sharding_registry.py (or
    # carry a per-site suppression naming the sanctioned builder)
    python scripts/dl4j_lint.py --select adhoc-out-shardings || exit 1
    # the mesh_sweep TRACKED series (tp step time, per-chip HBM) gate
    # the committed trajectory like every other bench series
    python scripts/bench_report.py --check BENCH_r*.json || exit 1
fi

rm -f /tmp/_t1.log
# force the 8-device host mesh WITHOUT clobbering ambient XLA_FLAGS
# (e.g. --xla_dump_to debugging); conftest.py does the same append for
# direct pytest invocations
case "${XLA_FLAGS:-}" in
    *xla_force_host_platform_device_count*) ;;
    *) XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8" ;;
esac
export XLA_FLAGS
# shellcheck disable=SC2086  # TARGET may list several suites
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest $TARGET -q \
    -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@" \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
