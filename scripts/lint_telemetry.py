#!/usr/bin/env python
"""Telemetry lint: no new bare ``_*_counter`` attributes outside monitor/.

PR 6 absorbed the scattered ad-hoc counters behind
``deeplearning4j_tpu.monitor.metrics()`` (and the ``record_counter``
one-liner). This check keeps the door shut: any module other than
``monitor/`` that assigns a ``self._<something>_counter`` attribute is
growing a new off-registry counter and fails the lint.

The two legacy per-instance counters (``_train_dispatches``,
``_eval_readbacks``) predate the naming rule and are mirrored into the
registry at every increment; they are intentionally NOT flagged (their
names do not match the ``_*_counter`` pattern, and tests rely on the
per-instance view).

Usage: python scripts/lint_telemetry.py   (exit 0 clean, 1 violations)
"""

from __future__ import annotations

import os
import re
import sys

# =(?!=) — assignment only, not `== ` comparisons
PATTERN = re.compile(r"self\._[A-Za-z0-9_]*_counter\b\s*=(?!=)")
PKG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "deeplearning4j_tpu")
EXEMPT_DIR = "monitor"


def main() -> int:
    violations = []
    for root, dirs, files in os.walk(os.path.abspath(PKG)):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        rel_root = os.path.relpath(root, os.path.abspath(PKG))
        if rel_root.split(os.sep)[0] == EXEMPT_DIR:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, 1):
                    if PATTERN.search(line):
                        violations.append(f"{path}:{lineno}: {line.strip()}")
    if violations:
        print("telemetry lint: bare _*_counter attributes outside "
              "monitor/ — use monitor.record_counter()/metrics() "
              "instead:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("telemetry lint: OK (no bare _*_counter attributes outside "
          "monitor/)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
