#!/usr/bin/env python
"""Flight-recorder postmortem: reconstruct a dead run's final timeline.

Rounds r04/r05 died to wedged device grants leaving one error line and no
record of what the process was doing. With ``DL4J_FLIGHT`` on, the flight
recorder (``deeplearning4j_tpu/monitor/flight.py``) leaves a bounded
segment ring on disk that survives SIGKILL; this script reads whatever
segments survived, prints the final timeline, and classifies the end
state:

- ``clean``     — the last run closed with status ``clean`` (or the
  recorder closed with nothing in flight)
- ``preempted`` — the run stopped at a chunk boundary on a preemption
  latch
- ``wedged``    — the process was ALIVE but stuck: writer heartbeats
  kept arriving long after the last progress record, or explicit wedge
  evidence (grant watchdog, chunk stall) ends the timeline — the
  BENCH_r04/r05 grant-wedge shape
- ``crashed``   — records stop abruptly (the heartbeats died with the
  progress): SIGKILL, OOM, segfault
- ``reacquired`` — clean-with-recovery: the run finished, but the
  timeline carries ``grant.reacquired`` evidence — a wedged grant was
  rescued by the lease protocol (resilience/lease.py) instead of
  costing the round. Counts as a healthy ending operationally, but is
  reported distinctly so chronic grant flapping stays visible
- ``drained``  — clean-and-planned: ``serve.drain`` evidence shows a
  replica was gracefully retired (streams migrated, zero recompute)
- ``shed-overload`` — clean-but-degraded: ``serve.shed`` evidence
  shows load was dropped (deadline expiry or criticality
  displacement); the serve-overload section splits the sheds by
  where the deadline caught them (queue vs in-flight)

Usage:
    python scripts/flight_report.py <flight-dir>            # human report
    python scripts/flight_report.py --json <flight-dir>     # machine-readable
    python scripts/flight_report.py --recent 40 <flight-dir>
    python scripts/flight_report.py --selftest              # write → kill -9
                                                            # → report round
                                                            # trip (CI)

Exit codes: 0 report produced (any end state), 1 selftest failure,
2 usage/load error. Wired into ``scripts/verify.sh --obs``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.monitor.flight import (  # noqa: E402
    classify_end_state,
    load_flight_records,
)


def _fmt_record(rec: dict, t0: float) -> str:
    t = rec.get("t_wall", t0)
    kind = rec.get("kind", "?")
    label = kind
    if kind == "span":
        label = f"span {rec.get('name', '?')}"
        dur = rec.get("duration_s")
        if dur is not None:
            label += f" ({dur:.3f}s)"
    detail = {k: v for k, v in rec.items()
              if k not in ("kind", "name", "t_wall", "t_mono", "_segment",
                           "span_id", "parent_id", "start_s", "end_s",
                           "duration_s", "attrs", "counters")}
    attrs = rec.get("attrs") or {}
    detail.update({k: v for k, v in attrs.items()
                   if isinstance(v, (str, int, float, bool))})
    extra = " ".join(f"{k}={v}" for k, v in sorted(detail.items()))
    return f"  +{t - t0:9.3f}s  {label:<28s} {extra}".rstrip()


def build_report(directory: str, recent: int = 25) -> dict:
    records = load_flight_records(directory)
    verdict = classify_end_state(records)
    runs = [r for r in records if r.get("kind") == "run.start"]
    chunks = sum(1 for r in records if r.get("kind") == "chunk.done")
    by_kind: dict = {}
    for r in records:
        k = r.get("kind", "?")
        if k == "span":
            k = f"span:{r.get('name', '?')}"
        by_kind[k] = by_kind.get(k, 0) + 1
    # serve-overload section: every shed/hedge/drain decision rides the
    # timeline as an event — split the sheds by where they happened
    # (queue-expiry vs in-flight expiry vs displacement) and count the
    # drains, so a storm postmortem reads the WHOLE story from records
    sheds = [r for r in records if r.get("kind") == "serve.shed"]
    serve = {}
    if sheds:
        by_where: dict = {}
        by_reason: dict = {}
        for r in sheds:
            by_where[r.get("where", "?")] = (
                by_where.get(r.get("where", "?"), 0) + 1)
            by_reason[r.get("reason", "?")] = (
                by_reason.get(r.get("reason", "?"), 0) + 1)
        serve["sheds"] = len(sheds)
        serve["sheds_by_where"] = dict(sorted(by_where.items()))
        serve["sheds_by_reason"] = dict(sorted(by_reason.items()))
        serve["expired_in_queue"] = sum(
            1 for r in sheds if r.get("where") == "queue"
            and r.get("reason") == "deadline")
        serve["expired_in_flight"] = sum(
            1 for r in sheds if r.get("where") == "in_flight")
    drains = [r for r in records if r.get("kind") == "serve.drain"]
    if drains:
        serve["drains"] = [
            {"replica": r.get("replica"), "migrated": r.get("migrated"),
             "fallback_failovers": r.get("fallback_failovers")}
            for r in drains]
    hedges = sum(1 for r in records if r.get("kind") == "serve.hedge")
    if hedges:
        serve["hedges"] = hedges
        serve["hedge_wins"] = sum(
            1 for r in records if r.get("kind") == "serve.hedge_win")
    return {
        "directory": directory,
        "end_state": verdict["end_state"],
        "status": verdict.get("status"),
        "evidence": verdict.get("evidence"),
        "n_records": len(records),
        "n_runs_started": len(runs),
        "n_chunks_done": chunks,
        "by_kind": dict(sorted(by_kind.items())),
        "serve_overload": serve or None,
        "timeline": records[-recent:],
    }


def print_report(report: dict, out=None) -> None:
    out = out or sys.stdout
    print(f"flight dir : {report['directory']}", file=out)
    print(f"end state  : {report['end_state'].upper()}"
          + (f" (status={report['status']})" if report.get("status")
             else ""), file=out)
    ev = report.get("evidence") or {}
    if "silent_s" in ev:
        print(f"silence    : {ev['silent_s']}s past last progress "
              f"(heartbeat every {ev.get('heartbeat_interval_s')}s)",
              file=out)
    if ev.get("n_reacquires"):
        print(f"reacquires : {ev['n_reacquires']} wedged grant(s) "
              "rescued by the lease protocol", file=out)
    serve = report.get("serve_overload")
    if serve:
        if serve.get("sheds"):
            print(f"sheds      : {serve['sheds']} "
                  f"(queue-expired {serve.get('expired_in_queue', 0)}, "
                  f"in-flight-expired {serve.get('expired_in_flight', 0)}) "
                  f"by reason {serve.get('sheds_by_reason')}", file=out)
        for d in serve.get("drains", ()):
            print(f"drain      : {d['replica']} migrated={d['migrated']} "
                  f"fallback_failovers={d['fallback_failovers']}",
                  file=out)
        if serve.get("hedges"):
            print(f"hedges     : {serve['hedges']} placed, "
                  f"{serve.get('hedge_wins', 0)} won", file=out)
    print(f"records    : {report['n_records']} surviving "
          f"({report['n_runs_started']} run(s) started, "
          f"{report['n_chunks_done']} chunk(s) completed)", file=out)
    for kind, n in report["by_kind"].items():
        print(f"  {kind:<28s} {n}", file=out)
    timeline = report["timeline"]
    if timeline:
        t0 = timeline[0].get("t_wall", 0.0)
        print(f"final timeline (last {len(timeline)} records):", file=out)
        for rec in timeline:
            print(_fmt_record(rec, t0), file=out)


def selftest() -> int:
    """The write → ``kill -9`` → report round trip the --obs gate runs:
    a child process records a run with a chunk in flight, the parent
    SIGKILLs it mid-run, and the surviving segments must classify as
    ``crashed`` with the run/chunk timeline intact. Stdlib-only — the
    child never imports jax."""
    import signal
    import subprocess
    import tempfile
    import time

    with tempfile.TemporaryDirectory() as d:
        flight_dir = os.path.join(d, "flight")
        child_code = f"""
import sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from deeplearning4j_tpu.monitor.flight import FlightRecorder, set_flight
from deeplearning4j_tpu.monitor.ledger import (
    ledger_chunk_done, ledger_chunk_start, ledger_run_start)
rec = FlightRecorder({flight_dir!r}, heartbeat_s_=0.05)
set_flight(rec)
ledger_run_start(model="selftest", epochs=10**6)
i = 0
while True:  # chunks forever, until the parent kills us
    ledger_chunk_start(epoch0=i)
    time.sleep(0.01)
    ledger_chunk_done(epoch0=i)
    i += 1
"""
        proc = subprocess.Popen([sys.executable, "-c", child_code])
        try:
            deadline = time.monotonic() + 30.0
            seen = 0
            while time.monotonic() < deadline:
                seen = sum(1 for r in load_flight_records(flight_dir)
                           if r.get("kind") == "chunk.done")
                if seen >= 3:
                    break
                if proc.poll() is not None:
                    print("flight selftest: child exited early "
                          f"(rc={proc.returncode})", file=sys.stderr)
                    return 1
                time.sleep(0.05)
            if seen < 3:
                print("flight selftest: no chunk records within 30s",
                      file=sys.stderr)
                return 1
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        report = build_report(flight_dir)
        print_report(report)
        if report["end_state"] != "crashed":
            print(f"flight selftest: expected end state 'crashed', got "
                  f"{report['end_state']!r}", file=sys.stderr)
            return 1
        if report["n_chunks_done"] < 3 or report["n_runs_started"] < 1:
            print("flight selftest: timeline incomplete", file=sys.stderr)
            return 1
        print("flight selftest: ok (kill -9 classified as crashed, "
              f"{report['n_chunks_done']} chunks reconstructed)")
        return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="flight-recorder postmortem report")
    ap.add_argument("directory", nargs="?",
                    help="flight segment directory "
                         "($DL4J_TELEMETRY_DIR/flight)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--recent", type=int, default=25,
                    help="timeline records to include (default 25)")
    ap.add_argument("--selftest", action="store_true",
                    help="write → kill -9 → report round trip (CI)")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.directory:
        ap.error("a flight directory is required (or --selftest)")
    if not os.path.isdir(args.directory):
        print(f"flight_report: {args.directory} is not a directory",
              file=sys.stderr)
        return 2
    report = build_report(args.directory, recent=args.recent)
    if args.json:
        print(json.dumps(report, default=str))
    else:
        print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
