#!/usr/bin/env python
"""dl4j-lint CLI: the JAX-aware ruleset over the tree (stdlib-only).

Usage:
    python scripts/dl4j_lint.py                      # full ruleset, whole tree
    python scripts/dl4j_lint.py --select bare-counter deeplearning4j_tpu
    python scripts/dl4j_lint.py --list-rules
    python scripts/dl4j_lint.py --update-baseline    # snapshot findings

Exit status: 0 when no NEW findings (inline-suppressed and baselined
findings do not fail the run), 1 otherwise. The shipped tree keeps the
baseline empty — see docs/static_analysis.md for the rule catalog,
suppression syntax (``# dl4j-lint: disable=<rule> -- reason``), and the
baseline workflow. The program-contract checker is the other half of the
gate: ``scripts/verify.sh --lint`` runs both.
"""

from __future__ import annotations

import argparse
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

from deeplearning4j_tpu.analysis import baseline as baseline_mod  # noqa: E402
from deeplearning4j_tpu.analysis.engine import (  # noqa: E402
    LintConfig,
    REPO_ROOT,
    default_scan_paths,
    iter_py_files,
    run_lint,
)
from deeplearning4j_tpu.analysis.rules import ALL_RULES  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dl4j-lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: "
                             "deeplearning4j_tpu/ and tests/)")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE",
                        help="run only these rule ids (repeatable / "
                             "comma-separated)")
    parser.add_argument("--baseline", default=baseline_mod.DEFAULT_BASELINE,
                        help="baseline file (default: "
                             ".dl4j-lint-baseline.json at the repo root)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding")
    parser.add_argument("--update-baseline", action="store_true",
                        help="snapshot current findings into the baseline "
                             "and exit 0")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="summary line only")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id:24s} {rule.doc}")
        print(f"{'suppression-missing-reason':24s} a "
              "'# dl4j-lint: disable=' comment without a '-- reason' "
              "tail (inert suppressions are findings)")
        return 0

    select = None
    if args.select:
        select = [r.strip() for chunk in args.select
                  for r in chunk.split(",") if r.strip()]
        if not select:
            # `--select ""` (e.g. an unset shell variable) must not turn
            # the gate vacuous by matching zero rules
            print("dl4j-lint: --select given but names no rules",
                  file=sys.stderr)
            return 2
        known = {r.id for r in ALL_RULES} | {"suppression-missing-reason"}
        unknown = sorted(set(select) - known)
        if unknown:
            print(f"dl4j-lint: unknown rule(s) {unknown}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2

    paths = args.paths or None
    if paths:
        # a typo'd or wrong path must not turn the gate vacuous: an
        # explicit argument that exists but yields zero Python files is
        # as dead as one that does not exist
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"dl4j-lint: path(s) do not exist: {missing}",
                  file=sys.stderr)
            return 2
        if not any(True for _ in iter_py_files(paths)):
            print(f"dl4j-lint: no Python files under {paths} — "
                  "nothing was checked", file=sys.stderr)
            return 2
    findings = run_lint(paths=paths, select=select, config=LintConfig())
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if args.update_baseline:
        preserve = ()
        if select or args.paths:
            # a narrowed run sees only a slice of the findings: replace
            # just that slice (rules run x paths scanned) and preserve
            # every other baselined entry, instead of silently dropping
            # them in a whole-file overwrite
            scan_paths = paths or default_scan_paths(REPO_ROOT)
            scanned = {os.path.relpath(p, REPO_ROOT).replace(os.sep, "/")
                       for p in iter_py_files(scan_paths)}
            sel = set(select) if select else None
            preserve = [
                e for e in baseline_mod.load_baseline(args.baseline).values()
                if (sel is not None and e.get("rule") not in sel)
                or e.get("path") not in scanned]
        n = baseline_mod.save_baseline(findings, path=args.baseline,
                                       preserve=preserve)
        print(f"dl4j-lint: baseline updated with {n} entr"
              f"{'y' if n == 1 else 'ies'} -> {args.baseline}")
        return 0

    known = ({} if args.no_baseline
             else baseline_mod.load_baseline(args.baseline))
    new, baselined = baseline_mod.partition_findings(findings, known)

    if new and not args.quiet:
        for f in new:
            print(f.format(), file=sys.stderr)
    by_rule = Counter(f.rule for f in new)
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    if new:
        print(f"dl4j-lint: {len(new)} new finding"
              f"{'' if len(new) == 1 else 's'} ({summary})"
              + (f"; {len(baselined)} baselined" if baselined else ""),
              file=sys.stderr)
        return 1
    n_rules = len(select) if select else len(list(ALL_RULES))
    print("dl4j-lint: OK"
          + (f" ({len(baselined)} baselined finding(s) unchanged)"
             if baselined else
             f" ({n_rules} rule{'' if n_rules == 1 else 's'} clean)"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
